"""Multi-host pod serving (workload/serve_dist.py): two real OS
processes rendezvous through a live catalog server, shard the model
over a 2-process global mesh, and answer HTTP byte-identically to a
single-host server of the same config."""
import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MODEL_FLAGS = [
    "--max-len", "48", "--d-model", "64", "--n-layers", "1",
    "--n-heads", "2", "--vocab", "128",
]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _sub_env() -> dict:
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)  # exactly 1 CPU device per process
    return env


def _reference(tokens, max_new, **kw):
    """Single-device generate with the server key convention."""
    from containerpilot_tpu.models.decode import generate
    from containerpilot_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )

    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=2, n_layers=1,
        d_ff=64 * 3 // 128 * 128 or 128, max_seq_len=48,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    seed = kw.pop("seed", 0)
    eos = kw.pop("eos_id", -1)
    out = generate(
        params, jnp.asarray([tokens], jnp.int32), cfg, max_new, 48,
        rng=jnp.stack(
            [jax.random.fold_in(jax.random.PRNGKey(seed), 0)]
        ),
        eos_id=eos, **kw,
    )
    from containerpilot_tpu.workload.serve import InferenceServer

    row = [int(t) for t in np.asarray(out)[0]]
    return InferenceServer._trim([row], max_new, eos)[0]


def test_two_process_pod_serves_http(tmp_path):
    catalog_port, coord_port, http_port = (
        _free_port(), _free_port(), _free_port()
    )
    env = _sub_env()
    catalog = subprocess.Popen(
        [sys.executable, "-m", "containerpilot_tpu",
         "-catalog-server", f"127.0.0.1:{catalog_port}"],
        cwd=REPO, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    procs = []
    logs = []
    try:
        deadline = time.monotonic() + 30
        while True:
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{catalog_port}/v1/health/service/x",
                    timeout=1,
                )
                break
            except Exception:
                if time.monotonic() > deadline:
                    pytest.fail("catalog never became ready")
                time.sleep(0.2)
        # the image's sitecustomize pins jax to the tunneled TPU in
        # every interpreter; the pod processes must pin CPU first
        wrapper = tmp_path / "serve_dist_cpu.py"
        wrapper.write_text(
            "import sys\n"
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            f"sys.path.insert(0, {REPO!r})\n"
            "from containerpilot_tpu.workload.serve_dist import main\n"
            "sys.exit(main())\n"
        )
        for pid in (0, 1):
            fh = open(tmp_path / f"pod{pid}.log", "w")
            logs.append(fh)
            procs.append(subprocess.Popen(
                [sys.executable, "-u", str(wrapper),
                 "--process-id", str(pid), "--num-processes", "2",
                 "--catalog", f"127.0.0.1:{catalog_port}",
                 "--coordinator-port", str(coord_port),
                 "--advertise-address", "127.0.0.1",
                 "--host", "127.0.0.1", "--port", str(http_port)]
                + MODEL_FLAGS,
                cwd=REPO, env=env, stdout=fh, stderr=subprocess.STDOUT,
            ))

        base = f"http://127.0.0.1:{http_port}"
        deadline = time.monotonic() + 240
        while True:
            try:
                urllib.request.urlopen(f"{base}/health", timeout=2)
                break
            except Exception:
                for i, proc in enumerate(procs):
                    assert proc.poll() is None, (
                        tmp_path / f"pod{i}.log"
                    ).read_text()[-3000:]
                if time.monotonic() > deadline:
                    pytest.fail(
                        "pod never became healthy:\n" + "\n".join(
                            (tmp_path / f"pod{i}.log").read_text()[-2000:]
                            for i in (0, 1)
                        )
                    )
                time.sleep(0.5)

        def post(body):
            req = urllib.request.Request(
                f"{base}/v1/generate",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=240) as resp:
                return json.loads(resp.read().decode())

        greedy = post({"tokens": [[1, 2, 3]], "max_new_tokens": 6})
        assert greedy["tokens"][0] == _reference([1, 2, 3], 6)

        sampled = post({
            "tokens": [[5, 6]], "max_new_tokens": 5,
            "temperature": 0.8, "top_k": 20, "seed": 9,
        })
        assert sampled["tokens"][0] == _reference(
            [5, 6], 5, temperature=0.8, top_k=20, seed=9
        )

        # the newer sampling knobs ride the broadcast payload too
        knobs = post({
            "tokens": [[7, 8, 9]], "max_new_tokens": 6,
            "min_new_tokens": 3, "frequency_penalty": 30.0,
        })
        assert knobs["tokens"][0] == _reference(
            [7, 8, 9], 6, min_new_tokens=3, frequency_penalty=30.0
        )

        # graceful pod shutdown: TERM on the frontend broadcasts the
        # stop; BOTH processes exit 0
        procs[0].send_signal(15)
        for i, proc in enumerate(procs):
            assert proc.wait(timeout=60) == 0, (
                tmp_path / f"pod{i}.log"
            ).read_text()[-3000:]
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        catalog.terminate()
        catalog.wait(timeout=10)
        for fh in logs:
            fh.close()
