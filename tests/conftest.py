"""Shared test configuration.

Supervisor tests are pure-host and need no accelerator. Workload tests
exercise multi-chip sharding on a virtual 8-device CPU mesh, so the JAX
platform must be pinned *before* jax is first imported anywhere.
"""
import asyncio
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# persistent XLA compile cache for the in-process JAX tier: the
# workload modules re-compile the same tiny-model programs on every
# suite run, which dominates wall time on this one-core box. Same
# cache dir the pod-boot subprocesses use (CONTAINERPILOT_COMPILE_CACHE
# in _sub_env), so a full suite warms it once. The default is
# PER-USER (tmpdir + username): a fixed shared /tmp path let one
# user's stale or corrupted entries poison another's suite on
# multi-user hosts. CONTAINERPILOT_COMPILE_CACHE stays the explicit
# override for both the in-process tier and the pod subprocesses.


def _default_compile_cache() -> str:
    import getpass
    import tempfile

    try:
        user = getpass.getuser()
    except Exception:  # no passwd entry (containers)
        user = f"uid{os.getuid()}" if hasattr(os, "getuid") else "user"
    return os.path.join(
        tempfile.gettempdir(), f"cp_test_compile_cache_{user}"
    )


COMPILE_CACHE_DIR = (
    os.environ.get("CONTAINERPILOT_COMPILE_CACHE")
    or _default_compile_cache()
)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", COMPILE_CACHE_DIR)
os.environ.setdefault(
    "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5"
)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# This image's sitecustomize registers a TPU PJRT plugin in every
# interpreter and pins jax_platforms to it, overriding the env var; the
# config update below (post-import, pre-first-use) is what actually
# lands the tests on the 8-device virtual CPU mesh.
import jax

jax.config.update("jax_platforms", "cpu")

import logging

import pytest

# ---------------------------------------------------------------------------
# Test tiers. The supervisor tier (all host-side packages: events, jobs,
# watches, config, control, discovery, telemetry, core, CLI) runs in
# ~2 minutes; the workload tier (models/ops/parallel on the virtual
# 8-device CPU mesh) dominates the full suite's wall time. Mirrors the
# reference's unit/integration split (its makefile runs
# scripts/unit_test.sh separately):
#     pytest -m supervisor      # fast tier (make test-fast)
#     pytest -m workload        # JAX tier
#     pytest                    # everything (make test)
# ---------------------------------------------------------------------------

_WORKLOAD_MODULES = {
    "test_workload", "test_window", "test_data", "test_flops",
    "test_capstone", "test_tuning", "test_slots",
    "test_serve_dist", "test_fleet", "test_chaos", "test_kvtier",
    "test_goodput",
}
_WORKLOAD_TESTS = {"test_fuzz_sample_logits_invariants"}


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "supervisor: host-side supervisor tier (fast, no JAX)"
    )
    config.addinivalue_line(
        "markers", "workload: JAX models/ops/parallel tier (slow)"
    )
    config.addinivalue_line(
        "markers",
        "slow: long-running scenarios excluded from tier-1 "
        "(`pytest -m 'not slow'`); `make chaos` runs them",
    )


def pytest_collection_modifyitems(config, items):
    for item in items:
        mod = item.module.__name__.rpartition(".")[2]
        if mod in _WORKLOAD_MODULES or (
            item.originalname or item.name
        ) in _WORKLOAD_TESTS:
            item.add_marker(pytest.mark.workload)
        else:
            item.add_marker(pytest.mark.supervisor)


@pytest.fixture(autouse=True)
def restore_containerpilot_logger():
    """LogConfig.init() mutates the shared 'containerpilot' logger
    (handlers, level, propagate); snapshot/restore per test so App
    tests can't break caplog-based tests elsewhere."""
    logger = logging.getLogger("containerpilot")
    saved = (list(logger.handlers), logger.level, logger.propagate)
    yield
    logger.handlers, logger.level, logger.propagate = (
        saved[0],
        saved[1],
        saved[2],
    )


@pytest.fixture
def run():
    """Run a coroutine to completion on a fresh event loop."""

    def _run(coro, timeout=30.0):
        return asyncio.run(asyncio.wait_for(coro, timeout=timeout))

    return _run


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Drop compiled executables when a test module finishes.

    The suite runs ~380 tests in ONE interpreter; by the tail of the
    session the process holds hundreds of live XLA executables and
    the CPU compiler starts degrading — observed as multi-minute
    compile stalls and, twice, a segfault inside
    backend_compile_and_load ~50 minutes in (the crashing test passes
    alone). Per-module cache clearing bounds that accumulation; the
    cross-module recompile cost is small because modules share almost
    no shapes."""
    yield
    jax.clear_caches()
