"""Catalog server tests: the ConsulBackend driven against our own
Consul-API-compatible daemon — the multi-host TPU-pod discovery path
(analog of the reference's real-Consul test server,
reference: discovery/test_server.go)."""
import asyncio
import time

import pytest

from containerpilot_tpu.discovery import (
    ConsulBackend,
    ServiceDefinition,
    ServiceRegistration,
)
from containerpilot_tpu.discovery.catalog_server import CatalogServer

PORT = 18501


def run_with_catalog(run, fn):
    async def scenario():
        server = CatalogServer("127.0.0.1", PORT)
        await server.run()
        backend = ConsulBackend(address=f"127.0.0.1:{PORT}")
        loop = asyncio.get_event_loop()
        try:
            return await loop.run_in_executor(None, fn, backend)
        finally:
            await server.stop()

    return run(scenario(), timeout=30)


def test_register_heartbeat_query_deregister(run):
    def fn(backend: ConsulBackend):
        reg = ServiceRegistration(
            id="trainer-host0", name="trainer", port=4000,
            address="10.0.0.1", ttl=10, tags=["v1"],
        )
        svc = ServiceDefinition(reg, backend)
        # critical until the first heartbeat
        changed, healthy = backend.check_for_upstream_changes("trainer")
        assert (changed, healthy) == (False, False)
        svc._register_sync("")  # registered, unchecked
        changed, healthy = backend.check_for_upstream_changes("trainer")
        assert (changed, healthy) == (False, False)  # not passing yet
        backend.update_ttl("service:trainer-host0", "ok", "pass")
        changed, healthy = backend.check_for_upstream_changes("trainer")
        assert (changed, healthy) == (True, True)
        instances = backend.instances("trainer")
        assert len(instances) == 1
        assert instances[0].address == "10.0.0.1"
        assert instances[0].port == 4000
        backend.service_deregister("trainer-host0")
        changed, healthy = backend.check_for_upstream_changes("trainer")
        assert (changed, healthy) == (True, False)
        return True

    assert run_with_catalog(run, fn)


def test_ttl_expiry_goes_critical(run):
    def fn(backend: ConsulBackend):
        reg = ServiceRegistration(
            id="web-h1", name="web", port=80, address="10.0.0.2", ttl=1,
        )
        backend.service_register(reg, status="passing")
        _c, healthy = backend.check_for_upstream_changes("web")
        assert healthy
        time.sleep(1.3)  # TTL 1s lapses
        changed, healthy = backend.check_for_upstream_changes("web")
        assert changed and not healthy
        # a fresh heartbeat revives it
        backend.update_ttl("service:web-h1", "ok", "pass")
        changed, healthy = backend.check_for_upstream_changes("web")
        assert changed and healthy
        return True

    assert run_with_catalog(run, fn)


def test_tag_filtering(run):
    def fn(backend: ConsulBackend):
        for i, tags in enumerate((["blue"], ["green"])):
            backend.service_register(
                ServiceRegistration(
                    id=f"api-{i}", name="api", port=80 + i,
                    address=f"10.0.1.{i}", ttl=30, tags=tags,
                ),
                status="passing",
            )
        assert len(backend.instances("api")) == 2
        assert len(backend.instances("api", tag="blue")) == 1
        return True

    assert run_with_catalog(run, fn)


def test_deregister_critical_service_after(run):
    async def scenario():
        server = CatalogServer("127.0.0.1", PORT)
        await server.run()
        backend = ConsulBackend(address=f"127.0.0.1:{PORT}")
        loop = asyncio.get_event_loop()

        def setup():
            backend.service_register(
                ServiceRegistration(
                    id="flaky-h1", name="flaky", port=80,
                    address="10.0.0.3", ttl=1,
                    deregister_critical_service_after="1s",
                ),
                status="passing",
            )

        await loop.run_in_executor(None, setup)
        await asyncio.sleep(3.5)  # TTL lapses, then reaper fires
        instances = await loop.run_in_executor(
            None, lambda: backend.instances("flaky")
        )
        reaped = "flaky-h1" not in server._entries
        await server.stop()
        return instances, reaped

    instances, reaped = run(scenario(), timeout=30)
    assert instances == []
    assert reaped
