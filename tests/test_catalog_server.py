"""Catalog server tests: the ConsulBackend driven against our own
Consul-API-compatible daemon — the multi-host TPU-pod discovery path
(analog of the reference's real-Consul test server,
reference: discovery/test_server.go)."""
import asyncio
import time

import pytest

from containerpilot_tpu.discovery import (
    ConsulBackend,
    ServiceDefinition,
    ServiceRegistration,
)
from containerpilot_tpu.discovery.catalog_server import CatalogServer

PORT = 18501


def run_with_catalog(run, fn):
    async def scenario():
        server = CatalogServer("127.0.0.1", PORT)
        await server.run()
        backend = ConsulBackend(address=f"127.0.0.1:{PORT}")
        loop = asyncio.get_event_loop()
        try:
            return await loop.run_in_executor(None, fn, backend)
        finally:
            await server.stop()

    return run(scenario(), timeout=30)


def test_register_heartbeat_query_deregister(run):
    def fn(backend: ConsulBackend):
        reg = ServiceRegistration(
            id="trainer-host0", name="trainer", port=4000,
            address="10.0.0.1", ttl=10, tags=["v1"],
        )
        svc = ServiceDefinition(reg, backend)
        # critical until the first heartbeat
        changed, healthy = backend.check_for_upstream_changes("trainer")
        assert (changed, healthy) == (False, False)
        svc._register_sync("")  # registered, unchecked
        changed, healthy = backend.check_for_upstream_changes("trainer")
        assert (changed, healthy) == (False, False)  # not passing yet
        backend.update_ttl("service:trainer-host0", "ok", "pass")
        changed, healthy = backend.check_for_upstream_changes("trainer")
        assert (changed, healthy) == (True, True)
        instances = backend.instances("trainer")
        assert len(instances) == 1
        assert instances[0].address == "10.0.0.1"
        assert instances[0].port == 4000
        backend.service_deregister("trainer-host0")
        changed, healthy = backend.check_for_upstream_changes("trainer")
        assert (changed, healthy) == (True, False)
        return True

    assert run_with_catalog(run, fn)


def test_ttl_expiry_goes_critical(run):
    def fn(backend: ConsulBackend):
        reg = ServiceRegistration(
            id="web-h1", name="web", port=80, address="10.0.0.2", ttl=1,
        )
        backend.service_register(reg, status="passing")
        _c, healthy = backend.check_for_upstream_changes("web")
        assert healthy
        time.sleep(1.3)  # TTL 1s lapses
        changed, healthy = backend.check_for_upstream_changes("web")
        assert changed and not healthy
        # a fresh heartbeat revives it
        backend.update_ttl("service:web-h1", "ok", "pass")
        changed, healthy = backend.check_for_upstream_changes("web")
        assert changed and healthy
        return True

    assert run_with_catalog(run, fn)


def test_tag_filtering(run):
    def fn(backend: ConsulBackend):
        for i, tags in enumerate((["blue"], ["green"])):
            backend.service_register(
                ServiceRegistration(
                    id=f"api-{i}", name="api", port=80 + i,
                    address=f"10.0.1.{i}", ttl=30, tags=tags,
                ),
                status="passing",
            )
        assert len(backend.instances("api")) == 2
        assert len(backend.instances("api", tag="blue")) == 1
        return True

    assert run_with_catalog(run, fn)


def test_deregister_critical_service_after(run):
    async def scenario():
        server = CatalogServer("127.0.0.1", PORT)
        await server.run()
        backend = ConsulBackend(address=f"127.0.0.1:{PORT}")
        loop = asyncio.get_event_loop()

        def setup():
            backend.service_register(
                ServiceRegistration(
                    id="flaky-h1", name="flaky", port=80,
                    address="10.0.0.3", ttl=1,
                    deregister_critical_service_after="1s",
                ),
                status="passing",
            )

        await loop.run_in_executor(None, setup)
        await asyncio.sleep(3.5)  # TTL lapses, then reaper fires
        instances = await loop.run_in_executor(
            None, lambda: backend.instances("flaky")
        )
        reaped = "flaky-h1" not in server._entries
        await server.stop()
        return instances, reaped

    instances, reaped = run(scenario(), timeout=30)
    assert instances == []
    assert reaped


def test_snapshot_restore_across_restart(run, tmp_path):
    """A supervised catalog daemon that dies and restarts must serve its
    last known registrations immediately (one re-armed TTL window)
    instead of an empty catalog (round-1 weak spot: in-memory SPOF)."""
    snap = str(tmp_path / "catalog.json")

    async def scenario():
        server = CatalogServer("127.0.0.1", PORT, snapshot_path=snap)
        await server.run()
        backend = ConsulBackend(address=f"127.0.0.1:{PORT}")
        loop = asyncio.get_event_loop()

        def setup():
            backend.service_register(
                ServiceRegistration(
                    id="db-h1", name="db", port=5432,
                    address="10.0.0.9", ttl=5, tags=["primary"],
                ),
                status="passing",
            )
            backend.service_register(
                ServiceRegistration(
                    id="cache-h1", name="cache", port=6379,
                    address="10.0.0.10", ttl=5,
                ),
            )  # registered but never passed: stays critical
        await loop.run_in_executor(None, setup)
        # stop() writes the final snapshot (simulates SIGTERM path);
        # a crash between journal ticks loses at most snapshot_every
        await server.stop()

        reborn = CatalogServer("127.0.0.1", PORT, snapshot_path=snap)
        await reborn.run()
        try:
            instances = await loop.run_in_executor(
                None, lambda: backend.instances("db")
            )
            crit = await loop.run_in_executor(
                None, lambda: backend.check_for_upstream_changes("cache")
            )
        finally:
            await reborn.stop()
        return instances, crit

    instances, crit = run(scenario(), timeout=30)
    # the passing service survived the restart with tags/address intact
    assert len(instances) == 1
    assert (instances[0].address, instances[0].port) == ("10.0.0.9", 5432)
    # the never-passing one restored as critical (no false health)
    assert crit == (False, False)


def test_snapshot_unreadable_starts_empty(run, tmp_path):
    snap = tmp_path / "corrupt.json"
    snap.write_text("{not json")

    async def scenario():
        server = CatalogServer("127.0.0.1", PORT, snapshot_path=str(snap))
        await server.run()
        backend = ConsulBackend(address=f"127.0.0.1:{PORT}")
        loop = asyncio.get_event_loop()
        try:
            return await loop.run_in_executor(
                None, lambda: backend.instances("anything")
            )
        finally:
            await server.stop()

    assert run(scenario(), timeout=30) == []


def test_snapshot_does_not_resurrect_expired_service(run, tmp_path):
    """A service whose TTL lapsed before the snapshot was written must
    restore as critical — never as a false healthy."""
    snap = str(tmp_path / "catalog.json")

    async def scenario():
        server = CatalogServer("127.0.0.1", PORT, snapshot_path=snap)
        await server.run()
        backend = ConsulBackend(address=f"127.0.0.1:{PORT}")
        loop = asyncio.get_event_loop()
        await loop.run_in_executor(None, lambda: backend.service_register(
            ServiceRegistration(id="dead-h1", name="dead", port=80,
                                address="10.0.0.11", ttl=1),
            status="passing",
        ))
        await asyncio.sleep(1.3)  # TTL lapses (status field still says
        await server.stop()       # "passing"; expiry is query-time)

        reborn = CatalogServer("127.0.0.1", PORT, snapshot_path=snap)
        await reborn.run()
        try:
            return await loop.run_in_executor(
                None, lambda: backend.instances("dead")
            )
        finally:
            await reborn.stop()

    assert run(scenario(), timeout=30) == []


def test_catalog_metrics_endpoint(run):
    import urllib.request

    async def scenario():
        server = CatalogServer("127.0.0.1", PORT)
        await server.run()
        backend = ConsulBackend(address=f"127.0.0.1:{PORT}")
        loop = asyncio.get_event_loop()
        await loop.run_in_executor(None, lambda: backend.service_register(
            ServiceRegistration(id="m-h1", name="m", port=80,
                                address="10.0.0.12", ttl=30),
            status="passing",
        ))

        def fetch():
            with urllib.request.urlopen(
                f"http://127.0.0.1:{PORT}/metrics", timeout=5
            ) as resp:
                return resp.read().decode()

        body = await loop.run_in_executor(None, fetch)
        await server.stop()
        return body

    body = run(scenario(), timeout=30)
    assert 'cp_catalog_services{status="passing"} 1' in body
    assert 'cp_catalog_services{status="critical"} 0' in body
    assert "cp_catalog_snapshot_enabled 0" in body


def test_backend_reuses_catalog_connection_per_thread(run):
    """TTL heartbeats and health polls from one thread ride ONE
    persistent keep-alive connection to the agent — the dial-per-call
    pattern is what made every heartbeat interval pay a connect."""

    async def scenario():
        server = CatalogServer("127.0.0.1", PORT)
        await server.run()
        backend = ConsulBackend(address=f"127.0.0.1:{PORT}")
        loop = asyncio.get_event_loop()

        def fn():
            backend.service_register(
                ServiceRegistration(
                    id="ka-1", name="ka", port=4000,
                    address="10.0.0.1", ttl=10,
                ),
                status="passing",
            )
            for _ in range(5):
                backend.update_ttl("service:ka-1", "ok", "pass")
                backend.check_for_upstream_changes("ka")
            backend.service_deregister("ka-1")

        try:
            # one worker thread => one kept backend connection
            await loop.run_in_executor(None, fn)
            http_server = server._server  # noqa: SLF001
            return (
                http_server.connections_accepted,
                http_server.requests_served,
            )
        finally:
            await server.stop()

    conns, reqs = run(scenario(), timeout=30)
    assert reqs == 12  # register + 5*(ttl+poll) + deregister
    assert conns == 1  # ... over a single dial


def test_snapshot_journal_runs_off_loop_and_redirties_on_failure(
    run, tmp_path
):
    """Regression for the CP-ASYNCREACH findings here: the journal's
    file write must leave the event loop (payload captured on-loop,
    I/O in the executor), and a failed write must re-dirty the
    journal so the next reap cadence retries instead of dropping the
    acknowledged mutations."""
    import threading

    snap = str(tmp_path / "snap.json")

    async def scenario():
        server = CatalogServer("127.0.0.1", PORT, snapshot_path=snap)
        loop_thread = threading.current_thread()
        writer_threads = []
        real_write = server._write_snapshot

        def spy(payload=None):
            writer_threads.append(threading.current_thread())
            return real_write(payload)

        server._write_snapshot = spy
        server._dirty = True
        await server._journal()
        assert server._dirty is False
        assert writer_threads
        assert all(t is not loop_thread for t in writer_threads)

        # unwritable target: the write fails, the dirt must survive
        server.snapshot_path = str(tmp_path / "no-such-dir" / "s.json")
        server._dirty = True
        await server._journal()
        assert server._dirty is True

        # the startup load leaves the loop the same way
        loader_threads = []
        reborn = CatalogServer("127.0.0.1", PORT, snapshot_path=snap)
        real_load = reborn._load_snapshot

        def load_spy():
            loader_threads.append(threading.current_thread())
            real_load()

        reborn._load_snapshot = load_spy
        await reborn.run()
        try:
            assert loader_threads
            assert all(t is not loop_thread for t in loader_threads)
        finally:
            await reborn.stop()

    run(scenario(), timeout=30)
