"""bench.py backend probing must survive transient tunnel wedges.

Round 2's single-attempt probe hit one unhealthy moment and the
round's entire workload-perf evidence came back empty.  These tests
pin the hardened behavior: retries with backoff, and per-bench
re-probe + one retry when a bench subprocess errors.
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import bench  # noqa: E402


def test_probe_retries_until_backend_answers(monkeypatch):
    calls = []

    def fake_once(timeout_s=180):
        calls.append(1)
        return "unreachable" if len(calls) < 3 else "tpu"

    sleeps = []
    monkeypatch.setattr(bench, "_probe_backend_once", fake_once)
    monkeypatch.setattr(bench.time, "sleep", sleeps.append)
    assert bench._probe_backend(attempts=4) == "tpu"
    assert len(calls) == 3
    # backoff grew between failed attempts
    assert sleeps == [10.0, 20.0]


def test_probe_gives_up_after_attempts(monkeypatch):
    monkeypatch.setattr(
        bench, "_probe_backend_once", lambda timeout_s=180: "unreachable"
    )
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    assert bench._probe_backend(attempts=3) == "unreachable"


def test_workload_benches_retry_failed_bench_once(monkeypatch):
    """One transient bench failure -> re-probe, retry, succeed."""
    probes = []

    def fake_probe(attempts=4, timeout_s=180):
        probes.append(attempts)
        return "tpu"

    runs = []

    def fake_sub(fn_name, timeout_s, env=None):
        runs.append(fn_name)
        if fn_name == "int8_bench" and runs.count("int8_bench") == 1:
            return {"error": "timeout after 1s"}
        return {"ok": fn_name}

    monkeypatch.setattr(bench, "_probe_backend", fake_probe)
    monkeypatch.setattr(bench, "_bench_subprocess", fake_sub)
    extras = bench.workload_benches()
    assert extras["int8_gemm"] == {"ok": "int8_bench", "retried": True}
    assert extras["attention"] == {"ok": "attention_bench"}
    assert runs.count("int8_bench") == 2
    # initial probe + the one re-probe before the retry
    assert len(probes) == 2


def test_workload_benches_record_both_errors_when_retry_fails(monkeypatch):
    monkeypatch.setattr(
        bench, "_probe_backend", lambda attempts=4, timeout_s=180: "tpu"
    )
    monkeypatch.setattr(
        bench,
        "_bench_subprocess",
        lambda fn_name, timeout_s, env=None: {"error": "exit 1"},
    )
    extras = bench.workload_benches()
    assert extras["training"]["error"] == "exit 1"
    assert extras["training"]["retry_error"] == "exit 1"


def test_workload_benches_skip_still_runs_host_overhead(monkeypatch):
    """No reachable TPU still returns REAL host_overhead and
    gateway_overhead entries (pinned to the cpu backend) next to the
    skip marker — the perf trajectory must never be empty just
    because the tunnel is down."""
    monkeypatch.setattr(
        bench, "_probe_backend", lambda attempts=4, timeout_s=180: "cpu"
    )
    calls = []

    def fake_sub(fn_name, timeout_s, env=None):
        calls.append((fn_name, env))
        return {"engine_host_overhead_ms": 0.1}

    monkeypatch.setattr(bench, "_bench_subprocess", fake_sub)
    extras = bench.workload_benches()
    assert "skipped" in extras
    assert extras["host_overhead"] == {"engine_host_overhead_ms": 0.1}
    assert extras["gateway_overhead"] == {"engine_host_overhead_ms": 0.1}
    assert extras["chaos_goodput"] == {"engine_host_overhead_ms": 0.1}
    assert extras["goodput_ledger"] == {"engine_host_overhead_ms": 0.1}
    assert extras["prefix_reuse"] == {"engine_host_overhead_ms": 0.1}
    assert extras["cold_start"] == {"engine_host_overhead_ms": 0.1}
    # only the any-backend benches ran, pinned to cpu
    assert calls == [
        ("host_overhead_bench", {"JAX_PLATFORMS": "cpu"}),
        ("gateway_overhead_bench", {"JAX_PLATFORMS": "cpu"}),
        ("goodput_ledger_bench", {"JAX_PLATFORMS": "cpu"}),
        ("chaos_goodput_bench", {"JAX_PLATFORMS": "cpu"}),
        ("prefix_reuse_bench", {"JAX_PLATFORMS": "cpu"}),
        ("cold_start_bench", {"JAX_PLATFORMS": "cpu"}),
    ]
