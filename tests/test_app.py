"""End-to-end App tests: real config files, real processes, the whole
generation loop (reference: core/app_test.go smoke tests plus the
integration-test scenarios' key assertions, SURVEY.md §4)."""
import asyncio
import os

import pytest

from containerpilot_tpu.client import ControlClient
from containerpilot_tpu.core import App
from containerpilot_tpu.core.flags import get_args


def write_config(tmp_path, text):
    path = tmp_path / "containerpilot.json5"
    path.write_text(text)
    return str(path)


def test_app_from_bad_config_raises(tmp_path):
    path = write_config(tmp_path, "{ bogus: true }")
    with pytest.raises(Exception):
        App.from_config_path(path)


def test_app_runs_jobs_to_completion(run, tmp_path):
    """All jobs complete -> the supervisor exits on its own
    (reference: core/app.go:110-140 escape hatch; the supervisor is
    not a server)."""
    marker = tmp_path / "ran.txt"
    path = write_config(
        tmp_path,
        """
        {
          stopTimeout: "1ms",
          jobs: [
            { name: "preStart", exec: "touch %s" },
            {
              name: "main",
              exec: ["/bin/sh", "-c", "exit 0"],
              when: { once: "exitSuccess", source: "preStart" },
            },
          ],
        }
        """
        % marker,
    )
    app = App.from_config_path(path)
    run(app.run(), timeout=20)
    assert marker.exists()
    assert all(j.is_complete for j in app.jobs)


def test_app_reload_via_control_socket(run, tmp_path):
    """-reload across the control socket swaps in a new generation with
    a fresh restart budget (reference: §3.5; integration
    test_config_reload / test_coprocess restart-budget reset)."""
    socket_path = str(tmp_path / "cp.socket")
    config = """
    {
      stopTimeout: "1ms",
      control: { socket: "%s" },
      jobs: [
        { name: "app", exec: "sleep 60" },
      ],
    }
    """ % socket_path
    path = write_config(tmp_path, config)

    async def scenario():
        app = App.from_config_path(path)
        run_task = asyncio.get_event_loop().create_task(app.run())
        await asyncio.sleep(0.3)
        gen1_bus = app.bus
        client = ControlClient(socket_path)
        loop = asyncio.get_event_loop()
        await loop.run_in_executor(None, client.reload)
        await asyncio.sleep(0.5)
        gen2_bus = app.bus
        assert gen2_bus is not gen1_bus, "reload must build a fresh bus"
        app.terminate()  # now the SIGTERM path ends generation 2
        await asyncio.wait_for(run_task, timeout=20)
        return True

    assert run(scenario(), timeout=30)


def test_app_terminate_runs_prestop_first(run, tmp_path):
    """SIGTERM: preStop runs during shutdown, before main's stopped
    (integration test_sigterm assertions)."""
    log_file = tmp_path / "order.log"
    path = write_config(
        tmp_path,
        """
        {
          stopTimeout: "1ms",
          jobs: [
            { name: "main", exec: "sleep 60", stopTimeout: "3s" },
            {
              name: "preStop",
              exec: ["/bin/sh", "-c", "echo prestop >> %s"],
              when: { once: "stopping", source: "main" },
            },
          ],
        }
        """
        % log_file,
    )

    async def scenario():
        app = App.from_config_path(path)
        run_task = asyncio.get_event_loop().create_task(app.run())
        await asyncio.sleep(0.3)
        app.terminate()
        await asyncio.wait_for(run_task, timeout=20)
        return log_file.read_text()

    assert "prestop" in run(scenario(), timeout=30)


def test_flags_dispatch():
    handler, params = get_args(["-version"])
    assert handler is not None
    handler2, params2 = get_args(["-config", "/tmp/x.json5"])
    assert handler2 is None
    assert params2["config_path"] == "/tmp/x.json5"
    handler3, _p = get_args(["-ping", "-config", "/tmp/x.json5"])
    assert handler3 is not None
