"""cpcheck tests: one firing and one non-firing fixture per rule ID,
pragma escape hatches, baseline workflow (including drift against the
committed baseline), the CLI/`make lint` gate, and the racecheck
runtime harness.

These are the analyzer's own unit tests; the rules' value against the
REAL codebase is enforced by test_baseline_matches_fresh_scan and
test_lint_gate below.
"""
import asyncio
import json
import os
import subprocess
import sys
import textwrap
import threading

import pytest

from containerpilot_tpu.analysis import (
    ALL_RULES,
    PROJECT_RULES,
    PROJECT_RULES_BY_ID,
    RULES_BY_ID,
    RaceCheck,
    build_project,
    diff_against_baseline,
    explain_stale,
    load_baseline,
    run_project_rules,
    scan_package,
    scan_source,
    write_baseline,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "containerpilot_tpu")


def findings_for(src: str, rule: str):
    return [
        f for f in scan_source(textwrap.dedent(src), "fixture.py")
        if f.rule == rule
    ]


# ---------------------------------------------------------------- rules

def test_rule_catalog_complete():
    ids = {r.rule_id for r in ALL_RULES}
    assert ids == {
        "CP-HOTSYNC", "CP-DONATE", "CP-LOCKPUB",
        "CP-SWALLOW", "CP-THREAD", "CP-TOPIC",
        "CP-ASYNCBLOCK", "CP-TASKLEAK", "CP-AWAITHOLD", "CP-RETRACE",
    }
    for rule in ALL_RULES:
        assert rule.__doc__, f"{rule.rule_id} must document itself"
        assert RULES_BY_ID[rule.rule_id] is rule
    project_ids = {r.rule_id for r in PROJECT_RULES}
    assert project_ids == {
        "CP-ASYNCREACH", "CP-HOTREACH", "CP-LOCKORDER", "CP-NOTEWIRE",
    }
    assert ids.isdisjoint(project_ids)
    for rule in PROJECT_RULES:
        assert rule.__doc__, f"{rule.rule_id} must document itself"
        assert PROJECT_RULES_BY_ID[rule.rule_id] is rule


def test_hotsync_fires_in_marked_function():
    src = """
    # cpcheck: hotpath
    def round(state):
        x = state.tokens.item()
        return x
    """
    found = findings_for(src, "CP-HOTSYNC")
    assert len(found) == 1 and found[0].scope == "round"


def test_hotsync_decorator_and_blocking_calls():
    src = """
    @hotpath
    def round(toks):
        time.sleep(0.1)
        a = np.asarray(toks)
        toks.block_until_ready()
        return a
    """
    assert len(findings_for(src, "CP-HOTSYNC")) == 3


def test_hotsync_silent_on_unmarked_function():
    src = """
    def warmup(state):
        state.tokens.block_until_ready()
        return state.tokens.item()
    """
    assert findings_for(src, "CP-HOTSYNC") == []


def test_hotsync_inline_disable_pragma():
    src = """
    # cpcheck: hotpath — the decode round
    def round(toks):
        host = np.asarray(jax.device_get(toks))  # cpcheck: disable=CP-HOTSYNC the one fetch
        return host
    """
    assert findings_for(src, "CP-HOTSYNC") == []


def test_donate_read_after_donation_fires():
    src = """
    def step(pool, row, cfg):
        new_pool = insert_row(pool, row, 0, cfg)
        return pool["k"]
    """
    found = findings_for(src, "CP-DONATE")
    assert len(found) == 1 and "`pool`" in found[0].message


def test_donate_rebind_by_same_call_is_clean():
    src = """
    def step(pool, state, params, cfg, chunk):
        pool = insert_row(pool, make_row(), 0, cfg)
        pool, state, toks = decode_slots_chunk(
            params, pool, state,
            cfg, chunk,
        )
        return pool, state, toks
    """
    assert findings_for(src, "CP-DONATE") == []


def test_donate_branch_aware():
    """A donation in one if-arm neither taints the sibling arm's read
    (mutually exclusive) nor is absolved by a sibling arm's rebind."""
    exclusive = """
    def f(state, row, cfg, cond):
        if cond:
            new = insert_row(state, row, 0, cfg)
            return new
        else:
            return state.total()
    """
    assert findings_for(exclusive, "CP-DONATE") == []
    after_join = """
    def f(state, row, cfg, cond):
        if cond:
            new = insert_row(state, row, 0, cfg)
        return state.total()
    """
    assert len(findings_for(after_join, "CP-DONATE")) == 1
    sibling_heal = """
    def f(state, row, cfg, cond):
        new = insert_row(state, row, 0, cfg)
        if cond:
            state = rebuild()
        else:
            x = state.total()
        return new
    """
    assert len(findings_for(sibling_heal, "CP-DONATE")) == 1


def test_hotpath_decorator_is_exported_noop():
    from containerpilot_tpu.analysis import hotpath

    @hotpath
    def f():
        return 7

    assert f() == 7


def test_donate_tracks_local_jit_bindings():
    src = """
    step = jax.jit(_step, donate_argnums=(0,))

    def train(state, batch):
        new_state = step(state, batch)
        return new_state, state.opt
    """
    found = findings_for(src, "CP-DONATE")
    assert len(found) == 1 and found[0].scope == "train"


def test_lockpub_fires_under_lock():
    src = """
    def deregister(self, rid):
        with self._lock:
            del self._replicas[rid]
            self.bus.publish(Event(EventCode.STOPPED, rid))
    """
    found = findings_for(src, "CP-LOCKPUB")
    assert len(found) == 1 and "bus.publish" in found[0].text


def test_lockpub_clean_outside_lock_and_in_nested_def():
    src = """
    def deregister(self, rid):
        with self._lock:
            del self._replicas[rid]
            def later():
                self.bus.publish(STOPPED)
        self.bus.publish(Event(EventCode.STOPPED, rid))
    """
    assert findings_for(src, "CP-LOCKPUB") == []


def test_swallow_fires_on_broad_pass():
    src = """
    def worker(self):
        try:
            self.step()
        except Exception:
            pass
    """
    assert len(findings_for(src, "CP-SWALLOW")) == 1


def test_swallow_allows_narrow_or_handled():
    src = """
    def worker(self):
        try:
            self.step()
        except ValueError:
            pass
        try:
            self.step()
        except Exception:
            log.exception("step failed")
    """
    assert findings_for(src, "CP-SWALLOW") == []


def test_thread_requires_explicit_daemon():
    src = """
    def start(self):
        self._t = threading.Thread(target=self._run)
        self._t.start()
    """
    assert len(findings_for(src, "CP-THREAD")) == 1


def test_thread_with_daemon_is_clean():
    src = """
    def start(self):
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()
    """
    assert findings_for(src, "CP-THREAD") == []


def test_topic_fires_on_inline_string_code():
    src = """
    def notify(bus, name):
        bus.publish(Event("exitSuccess", name))
    """
    found = findings_for(src, "CP-TOPIC")
    assert len(found) == 1 and "exitSuccess" in found[0].message


def test_topic_clean_on_registry_codes():
    src = """
    def notify(bus, name):
        bus.publish(Event(EventCode.EXIT_SUCCESS, name))
        bus.publish(GLOBAL_SHUTDOWN)
    """
    assert findings_for(src, "CP-TOPIC") == []


def test_disable_pragma_comma_in_justification_is_not_a_rule():
    """Prose after the rule ids may contain commas without widening
    the suppression to phantom rule names."""
    src = """
    def f(self, bus):
        with self._lock:
            bus.publish(GS)  # cpcheck: disable=CP-SWALLOW host-side only, no fan-out here
    """
    # CP-LOCKPUB still fires: only CP-SWALLOW was named; "no fan-out
    # here" must not parse as rules "NO"/...
    assert len(findings_for(src, "CP-LOCKPUB")) == 1


def test_disable_pragma_suppresses_named_rule_only():
    src = """
    def worker(self):
        try:
            self.step()
        except Exception:  # cpcheck: disable=CP-SWALLOW justified because test
            pass
        try:
            self.step()
        except Exception:  # cpcheck: disable=CP-TOPIC wrong rule id
            pass
    """
    assert len(findings_for(src, "CP-SWALLOW")) == 1


# ------------------------------------------- asyncio-era rules (PR 11)

def test_asyncblock_fires_on_blocking_calls():
    src = """
    async def handler(self, req):
        time.sleep(0.1)
        data = open(self.path).read()
        arr = jax.device_get(self.toks)
        jax.device_put(arr)
        out = subprocess.run(["ls"])
        return arr
    """
    found = findings_for(src, "CP-ASYNCBLOCK")
    assert len(found) == 5
    assert all(f.scope == "handler" for f in found)


def test_asyncblock_result_join_by_dataflow():
    """`.result()`/`.join()` fire only on receivers born from
    executor.submit / threading.Thread — `"".join(...)` and a done
    asyncio task's `.result()` are innocent."""
    src = """
    async def handler(self, ex):
        fut = ex.submit(work)
        y = fut.result()
        t = threading.Thread(target=work, daemon=True)
        t.join()
        s = ",".join(str(i) for i in y)
        done, _ = await asyncio.wait({task})
        return task.result()
    """
    found = findings_for(src, "CP-ASYNCBLOCK")
    assert len(found) == 2
    assert {f.line for f in found} == {4, 6}


def test_asyncblock_clean_sync_def_and_executor_heal():
    """Sync defs aren't the loop's problem; nested defs run on the
    executor; run_in_executor/to_thread arguments are the sanctioned
    escape and heal the finding."""
    src = """
    def sync_helper(path):
        time.sleep(0.1)
        return open(path).read()

    async def handler(self, loop, path):
        def work():
            return jax.device_get(self.toks)
        healed = await loop.run_in_executor(None, work)
        also = await asyncio.to_thread(sync_helper, path)
        return healed, also
    """
    assert findings_for(src, "CP-ASYNCBLOCK") == []


def test_asyncblock_inline_disable_pragma():
    src = """
    async def handler(self):
        time.sleep(0.001)  # cpcheck: disable=CP-ASYNCBLOCK sub-ms jitter by design, measured
        return 1
    """
    assert findings_for(src, "CP-ASYNCBLOCK") == []


def test_taskleak_fires_on_discarded_task():
    src = """
    def start(self):
        asyncio.create_task(self._loop())
        asyncio.get_event_loop().create_task(self._beat())
        asyncio.ensure_future(self._poll())
    """
    found = findings_for(src, "CP-TASKLEAK")
    assert len(found) == 3


def test_taskleak_heals_when_stored_awaited_or_chained():
    src = """
    def start(self):
        self._task = asyncio.create_task(self._loop())
        asyncio.create_task(self._beat()).add_done_callback(done)
        tasks.append(asyncio.ensure_future(self._poll()))

    async def once(self):
        await asyncio.create_task(self._once())
    """
    assert findings_for(src, "CP-TASKLEAK") == []


def test_awaithold_fires_under_thread_lock():
    src = """
    async def flush(self):
        with self._lock:
            await self._drain()
    """
    found = findings_for(src, "CP-AWAITHOLD")
    assert len(found) == 1 and found[0].scope == "flush"


def test_awaithold_fires_on_async_for_and_async_with():
    """`async for`/`async with` suspend at __anext__/__aenter__ with
    the thread lock held — same hazard, different node."""
    src = """
    async def relay(self):
        with self._lock:
            async for chunk in self._stream:
                self._buf.append(chunk)

    async def enter(self):
        with self._lock:
            async with self._session:
                pass
    """
    found = findings_for(src, "CP-AWAITHOLD")
    assert {f.scope for f in found} == {"relay", "enter"}


def test_awaithold_clean_asyncio_lock_and_nested_def():
    """`async with` IS the fix (asyncio.Lock is exempt by shape), a
    nested def's await runs later, and awaiting after release is the
    discipline the rule pushes toward."""
    src = """
    async def flush(self):
        async with self._alock:
            await self._drain()
        with self._lock:
            def later():
                return self._drain()
            snapshot = list(self._pending)
        await self._deliver(snapshot)
    """
    assert findings_for(src, "CP-AWAITHOLD") == []


def test_retrace_fires_on_varying_args_in_hotpath():
    src = """
    step = jax.jit(_step)

    # cpcheck: hotpath
    def round(self, batch, key):
        a = step(batch, len(batch))
        b = step(batch, f"bucket-{key}")
        c = step(batch, self.cache[key])
        d = lax.scan(body, carry, xs[key])
        return a, b, c, d
    """
    found = findings_for(src, "CP-RETRACE")
    assert len(found) == 4
    assert "recompile" in found[0].message


def test_retrace_fires_on_while_loop_step_program():
    """The fused decode window's shape: a ``lax.while_loop`` step
    program dispatched in a hot path with Python-varying operands is
    the same silent-recompile trap as a varying-arg jit call."""
    src = """
    # cpcheck: hotpath
    def dispatch_window(self, pool, state, batch):
        out = lax.while_loop(cond, body, (pool, state, len(batch)))
        return out
    """
    found = findings_for(src, "CP-RETRACE")
    assert len(found) == 1 and "recompile" in found[0].message


def test_retrace_clean_on_stable_while_loop():
    """A while_loop window driven by stable operands (the shipped
    shape: static rounds/chunk, device budgets) is clean — and a cold
    warmup path may shape-probe freely."""
    src = """
    # cpcheck: hotpath
    def dispatch_window(self, pool, state, budget):
        out = lax.while_loop(cond, body, (pool, state, budget))
        return out

    def warm(self, pool, state, batch):
        return lax.while_loop(cond, body, (pool, state, len(batch)))
    """
    assert findings_for(src, "CP-RETRACE") == []


def test_hotsync_on_while_loop_step_program():
    """CP-HOTSYNC over the fused-window driver shape: the one
    deliberate per-window fetch must carry its pragma (firing twin:
    the same fetch without one)."""
    firing = """
    # cpcheck: hotpath — the fused window fetch
    def tokens(self, handle):
        toks, run = handle
        host = np.asarray(jax.device_get(toks))
        return host
    """
    assert len(findings_for(firing, "CP-HOTSYNC")) == 2
    clean = """
    # cpcheck: hotpath — the fused window fetch
    def tokens(self, handle):
        toks, run = handle
        host, rounds_run = jax.device_get((toks, run))  # cpcheck: disable=CP-HOTSYNC the per-window token fetch
        return host, rounds_run
    """
    assert findings_for(clean, "CP-HOTSYNC") == []


def test_retrace_clean_on_stable_args_or_cold_path():
    """Stable operands in the hot path are fine; a warmup path may
    shape-probe all it wants; constant subscripts are static."""
    src = """
    step = jax.jit(_step)

    # cpcheck: hotpath
    def round(self, batch, params, cfg):
        out = step(batch, params, cfg)
        out = step(out, self.buckets[0])
        out = step(out, self.buckets[-1])
        return step(out, self.shapes[1, 0])

    def warmup(self, batch):
        return step(batch, len(batch))
    """
    assert findings_for(src, "CP-RETRACE") == []


# ---------------------------------- interprocedural rules (callgraph)

def project_findings(sources: dict, rule: str):
    """Run the interprocedural rules over a multi-module fixture."""
    project = build_project(
        {p: textwrap.dedent(s) for p, s in sources.items()}
    )
    return [f for f in run_project_rules(project) if f.rule == rule]


def test_asyncreach_fires_through_sync_hops():
    src = """
    import time

    def inner():
        time.sleep(1.0)

    def middle():
        inner()

    async def handler():
        middle()
    """
    found = findings_for(src, "CP-ASYNCREACH")
    assert len(found) == 1
    assert found[0].scope == "handler"
    assert "time.sleep" in found[0].message
    assert "inner" in found[0].message


def test_asyncreach_respects_hop_bound():
    """Four sync hops is beyond the documented bound of three — the
    rule stays quiet rather than report ever-fuzzier chains."""
    src = """
    import time

    def h4():
        time.sleep(1.0)

    def h3():
        h4()

    def h2():
        h3()

    def h1():
        h2()

    async def handler():
        h1()
    """
    assert findings_for(src, "CP-ASYNCREACH") == []


def test_asyncreach_executor_heal_at_any_hop():
    src = """
    import asyncio
    import time

    def inner():
        time.sleep(1.0)

    async def healed_at_root():
        await asyncio.get_running_loop().run_in_executor(None, inner)

    def middle():
        loop.run_in_executor(None, inner)

    async def healed_mid_chain():
        middle()
    """
    assert findings_for(src, "CP-ASYNCREACH") == []


def test_asyncreach_inline_disable_pragma():
    src = """
    import time

    def inner():
        time.sleep(1.0)

    async def handler():
        inner()  # cpcheck: disable=CP-ASYNCREACH intentional startup block
    """
    assert findings_for(src, "CP-ASYNCREACH") == []


def test_asyncreach_cross_module():
    found = project_findings({
        "util.py": """
            import time

            def backoff():
                time.sleep(0.5)
        """,
        "svc.py": """
            from util import backoff

            async def retry():
                backoff()
        """,
    }, "CP-ASYNCREACH")
    assert len(found) == 1
    assert found[0].file == "svc.py"
    assert "util.py" in found[0].message


def test_hotreach_inherits_through_helpers():
    src = """
    import numpy as np

    def fetch(x):
        return np.asarray(x)

    def relay(x):
        return fetch(x)

    # cpcheck: hotpath
    def round(x):
        return relay(x)
    """
    found = findings_for(src, "CP-HOTREACH")
    assert len(found) == 1
    assert found[0].scope == "fetch"
    assert "relay" in found[0].message


def test_hotreach_silent_without_hot_root():
    src = """
    import numpy as np

    def fetch(x):
        return np.asarray(x)

    def round(x):
        return fetch(x)
    """
    assert findings_for(src, "CP-HOTREACH") == []


def test_hotreach_honors_twin_rule_pragma_and_def_optout():
    """A helper's existing CP-HOTSYNC line pragma heals the inherited
    check; a CP-HOTREACH pragma on the def line opts the whole
    function out of heat inheritance (deliberately cold helpers)."""
    line_pragma = """
    import numpy as np

    def fetch(x):
        return np.asarray(x)  # cpcheck: disable=CP-HOTSYNC one-time fetch

    # cpcheck: hotpath
    def round(x):
        return fetch(x)
    """
    assert findings_for(line_pragma, "CP-HOTREACH") == []

    def_optout = """
    import numpy as np

    def dump(x):  # cpcheck: disable=CP-HOTREACH debug-only dump
        print(x)
        return np.asarray(x)

    # cpcheck: hotpath
    def round(x):
        return dump(x)
    """
    assert findings_for(def_optout, "CP-HOTREACH") == []


def test_hotreach_checks_retrace_in_inherited_helper():
    src = """
    import jax

    step = jax.jit(_step)

    def relay(self, batch):
        return step(batch, len(batch))

    # cpcheck: hotpath
    def round(self, batch):
        return relay(self, batch)
    """
    found = findings_for(src, "CP-HOTREACH")
    assert len(found) == 1
    assert "len(batch)" in found[0].text


def test_lockorder_cycle_reports_both_witnesses():
    src = """
    import threading

    lock_a = threading.Lock()
    lock_b = threading.Lock()

    def take_b():
        with lock_b:
            pass

    def a_then_b():
        with lock_a:
            take_b()

    def take_a():
        with lock_a:
            pass

    def b_then_a():
        with lock_b:
            take_a()
    """
    found = findings_for(src, "CP-LOCKORDER")
    assert len(found) == 1
    msg = found[0].message
    assert "lock_a" in msg and "lock_b" in msg
    assert "a_then_b" in msg and "b_then_a" in msg


def test_lockorder_consistent_order_is_clean():
    src = """
    import threading

    lock_a = threading.Lock()
    lock_b = threading.Lock()

    def take_b():
        with lock_b:
            pass

    def one():
        with lock_a:
            take_b()

    def two():
        with lock_a:
            with lock_b:
                pass
    """
    assert findings_for(src, "CP-LOCKORDER") == []


def test_lockorder_reentry_is_not_a_cycle():
    src = """
    import threading

    lock = threading.RLock()

    def inner():
        with lock:
            pass

    def outer():
        with lock:
            inner()
    """
    assert findings_for(src, "CP-LOCKORDER") == []


def test_notewire_missing_parser_and_bypass():
    found = project_findings({
        "reg.py": """
            from dataclasses import dataclass

            @dataclass(frozen=True)
            class NoteField:
                name: str
                produce: object
                parse: object
                doc: str = ""

            def _ident(raw):
                return raw

            FIELDS = (
                NoteField(name="kv", produce=_ident, parse=_ident),
                NoteField(name="gp", produce=_ident, parse=None),
            )
        """,
        "prod.py": """
            def note(v):
                return "kv=" + v
        """,
    }, "CP-NOTEWIRE")
    messages = "\n".join(f.message for f in found)
    assert any(f.file == "reg.py" for f in found), messages
    assert "gp" in messages
    assert any(f.file == "prod.py" for f in found), messages


def test_notewire_unregistered_consumption():
    found = project_findings({
        "reg.py": """
            from dataclasses import dataclass

            @dataclass(frozen=True)
            class NoteField:
                name: str
                produce: object
                parse: object

            def _ident(raw):
                return raw

            FIELDS = (
                NoteField(name="kv", produce=_ident, parse=_ident),
            )
        """,
        "gw.py": """
            from notes import split_note

            def apply(raw):
                fields = split_note(raw)
                good = fields.get("kv", "")
                bad = fields.get("zz", "")
                return good, bad
        """,
    }, "CP-NOTEWIRE")
    assert len(found) == 1
    assert found[0].file == "gw.py"
    assert "zz" in found[0].message


def test_notewire_silent_without_registry():
    """Fixtures (and projects) with no NoteField FIELDS registry are
    none of this rule's business."""
    src = """
    def note(v):
        return "kv=" + v
    """
    assert findings_for(src, "CP-NOTEWIRE") == []


# ----------------------------------------------- call graph internals

def test_callgraph_resolves_self_methods_and_instances():
    project = build_project({"mod.py": textwrap.dedent("""
        class Engine:
            def run(self):
                self.step()

            def step(self):
                pass

        engine = Engine()

        def drive():
            engine.step()
    """)})
    g = project.graph
    run_edges = {e.callee for e in g.edges_from["mod:Engine.run"]}
    assert "mod:Engine.step" in run_edges
    drive_edges = {e.callee for e in g.edges_from["mod:drive"]}
    assert "mod:Engine.step" in drive_edges


def test_callgraph_partial_and_spawn_are_deferred():
    """partial/spawn targets are recorded — but as deferred edge
    kinds the sync-reachability walk must not traverse."""
    project = build_project({"mod.py": textwrap.dedent("""
        import asyncio
        import functools
        import time

        def worker():
            time.sleep(1.0)

        def build():
            return functools.partial(worker, 1)

        async def kick():
            asyncio.create_task(aworker())

        async def aworker():
            pass
    """)})
    g = project.graph
    kinds = {
        (e.callee, e.kind)
        for edges in g.edges_from.values()
        for e in edges
    }
    assert ("mod:worker", "partial") in kinds
    assert ("mod:aworker", "spawn") in kinds
    reached = {
        info.scope for info, _ in g.sync_reachable("mod:build")
    }
    assert "worker" not in reached


def test_callgraph_unknown_edges_are_recorded_not_guessed():
    project = build_project({"mod.py": textwrap.dedent("""
        def f(x):
            x.frobnicate()
    """)})
    g = project.graph
    assert g.edges_from.get("mod:f", []) == []
    assert any(
        u.caller == "mod:f" and "frobnicate" in u.name
        for u in g.unknown
    )
    assert all(u.reason for u in g.unknown)


def test_callgraph_sync_reachable_yields_witness_path():
    project = build_project({"mod.py": textwrap.dedent("""
        def c():
            pass

        def b():
            c()

        def a():
            b()
    """)})
    g = project.graph
    reached = {
        info.scope: path for info, path in g.sync_reachable("mod:a")
    }
    assert set(reached) == {"b", "c"}
    assert [e.callee for e in reached["c"]] == ["mod:b", "mod:c"]


# ------------------------------------------------------------- baseline

def test_baseline_matches_fresh_scan():
    """The committed baseline exactly mirrors a fresh scan: no new
    findings (would fail CI anyway) and no stale entries (fixed debt
    must leave the ledger)."""
    findings = scan_package(PACKAGE, relative_to=REPO)
    new, stale = diff_against_baseline(findings, load_baseline())
    assert new == [], "new findings:\n" + "\n".join(
        f.render() for f in new
    )
    assert stale == [], "stale baseline entries (run make lint-baseline):\n" + "\n".join(
        str(e) for e in stale
    )


def test_baseline_multiset_semantics(tmp_path):
    src = """
    def a(self):
        try:
            self.x()
        except Exception:
            pass

    def b(self):
        try:
            self.x()
        except Exception:
            pass
    """
    findings = [
        f for f in scan_source(textwrap.dedent(src), "m.py")
        if f.rule == "CP-SWALLOW"
    ]
    assert len(findings) == 2
    # one baseline entry cannot absolve two identical findings
    path = str(tmp_path / "baseline.json")
    write_baseline(findings[:1], path)
    new, stale = diff_against_baseline(findings, load_baseline(path))
    assert len(new) == 1 and stale == []


def test_explain_stale_names_the_cause(tmp_path):
    """`make lint-baseline` / the lint failure must say WHY an entry
    went stale: edited line text (fingerprint drift) vs fixed debt."""
    src = """
    def a(self):
        try:
            self.x()
        except Exception:
            pass
    """
    findings = [
        f for f in scan_source(textwrap.dedent(src), "m.py")
        if f.rule == "CP-SWALLOW"
    ]
    path = str(tmp_path / "baseline.json")
    write_baseline(findings, path)

    # drift: the baselined line's text changed, same scope still fires
    drifted = [
        f for f in scan_source(
            textwrap.dedent(src).replace(
                "except Exception:", "except Exception:  # noqa"
            ),
            "m.py",
        )
        if f.rule == "CP-SWALLOW"
    ]
    new, stale = diff_against_baseline(drifted, load_baseline(path))
    assert len(new) == 1 and len(stale) == 1
    lines = explain_stale(new, stale)
    assert len(lines) == 1
    assert "line text drifted" in lines[0]
    assert "m.py [a] CP-SWALLOW" in lines[0]

    # fixed: the finding is gone entirely
    new, stale = diff_against_baseline([], load_baseline(path))
    lines = explain_stale(new, stale)
    assert len(lines) == 1
    assert "finding no longer present" in lines[0]
    assert "make lint-baseline" in lines[0]


def test_cli_reports_stale_entries_with_reason(tmp_path):
    """End to end: a full scan against a baseline holding a bogus
    entry warns (still exit 0) and explains the staleness."""
    entries = load_baseline()
    entries = entries + [{
        "rule": "CP-SWALLOW", "file": "containerpilot_tpu/gone.py",
        "scope": "f", "text": "pass",
    }]
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps({"version": 1, "entries": entries}))
    proc = _run_cli("--baseline", str(path), "--no-compileall")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "stale baseline entr" in proc.stdout
    assert "finding no longer present" in proc.stdout


def test_write_baseline_preserves_reasons(tmp_path):
    findings = [
        f for f in scan_source(
            "try:\n    pass\nexcept Exception:\n    pass\n", "m.py"
        )
        if f.rule == "CP-SWALLOW"
    ]
    path = str(tmp_path / "baseline.json")
    write_baseline(findings, path)
    data = json.load(open(path))
    data["entries"][0]["reason"] = "because"
    json.dump(data, open(path, "w"))
    write_baseline(findings, path)
    assert json.load(open(path))["entries"][0]["reason"] == "because"


# ------------------------------------------------------------ CLI gate

def _run_cli(*argv, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "containerpilot_tpu.analysis", *argv],
        cwd=cwd, capture_output=True, text=True, timeout=120,
    )


def test_lint_gate():
    """The tier-1 gate: the exact `make lint` body must pass on the
    tree as committed."""
    proc = _run_cli()
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout


def test_lint_gate_fails_on_seeded_violation(tmp_path):
    bad = tmp_path / "seeded.py"
    bad.write_text(
        "# cpcheck: hotpath\n"
        "def round(toks):\n"
        "    toks.block_until_ready()\n"
    )
    proc = _run_cli("--files", str(bad))
    assert proc.returncode == 1
    assert "CP-HOTSYNC" in proc.stdout


def test_lint_gate_fails_on_seeded_lockpub(tmp_path):
    bad = tmp_path / "seeded.py"
    bad.write_text(
        "def f(self):\n"
        "    with self._lock:\n"
        "        self.bus.publish(GLOBAL_SHUTDOWN)\n"
    )
    proc = _run_cli("--files", str(bad))
    assert proc.returncode == 1
    assert "CP-LOCKPUB" in proc.stdout


def test_lint_gate_fails_on_seeded_asyncblock(tmp_path):
    bad = tmp_path / "seeded.py"
    bad.write_text(
        "async def handler(req):\n"
        "    time.sleep(1)\n"
    )
    proc = _run_cli("--files", str(bad))
    assert proc.returncode == 1
    assert "CP-ASYNCBLOCK" in proc.stdout


def test_lint_gate_fails_on_seeded_taskleak(tmp_path):
    bad = tmp_path / "seeded.py"
    bad.write_text(
        "def start(self):\n"
        "    asyncio.create_task(self._loop())\n"
    )
    proc = _run_cli("--files", str(bad))
    assert proc.returncode == 1
    assert "CP-TASKLEAK" in proc.stdout


def test_lint_gate_fails_on_seeded_asyncreach(tmp_path):
    bad = tmp_path / "seeded.py"
    bad.write_text(
        "import time\n"
        "def helper():\n"
        "    time.sleep(1.0)\n"
        "async def handler():\n"
        "    helper()\n"
    )
    proc = _run_cli("--files", str(bad))
    assert proc.returncode == 1
    assert "CP-ASYNCREACH" in proc.stdout


def test_lint_gate_fails_on_seeded_hotreach(tmp_path):
    bad = tmp_path / "seeded.py"
    bad.write_text(
        "import numpy as np\n"
        "def fetch(x):\n"
        "    return np.asarray(x)\n"
        "# cpcheck: hotpath\n"
        "def round(x):\n"
        "    return fetch(x)\n"
    )
    proc = _run_cli("--files", str(bad))
    assert proc.returncode == 1
    assert "CP-HOTREACH" in proc.stdout


def test_lint_gate_fails_on_seeded_lockorder(tmp_path):
    bad = tmp_path / "seeded.py"
    bad.write_text(
        "import threading\n"
        "lock_a = threading.Lock()\n"
        "lock_b = threading.Lock()\n"
        "def take_b():\n"
        "    with lock_b:\n"
        "        pass\n"
        "def ab():\n"
        "    with lock_a:\n"
        "        take_b()\n"
        "def take_a():\n"
        "    with lock_a:\n"
        "        pass\n"
        "def ba():\n"
        "    with lock_b:\n"
        "        take_a()\n"
    )
    proc = _run_cli("--files", str(bad))
    assert proc.returncode == 1
    assert "CP-LOCKORDER" in proc.stdout


def test_lint_gate_fails_on_seeded_notewire(tmp_path):
    """The real fleet/notes.py registry is in the project the --files
    scan builds, so an ad-hoc `\"kv=\" +` concat in the seeded file is
    a bypass of it."""
    bad = tmp_path / "seeded.py"
    bad.write_text(
        "def note(v):\n"
        "    return \"kv=\" + v\n"
    )
    proc = _run_cli("--files", str(bad))
    assert proc.returncode == 1
    assert "CP-NOTEWIRE" in proc.stdout


def test_cli_rejects_partial_baseline_write(tmp_path):
    """--write-baseline over a partial --files scan would silently
    drop every other file's justified entries; it must be refused."""
    f = tmp_path / "x.py"
    f.write_text("x = 1\n")
    proc = _run_cli("--files", str(f), "--write-baseline")
    assert proc.returncode == 2  # argparse usage error


def test_cli_list_rules():
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    for rule in list(ALL_RULES) + list(PROJECT_RULES):
        assert rule.rule_id in proc.stdout


def test_make_lint_target():
    """`make lint` is wired to the analyzer (satellite contract)."""
    import shutil

    if shutil.which("make") is None:
        pytest.skip("make not available")
    proc = subprocess.run(
        ["make", "lint"], cwd=REPO, capture_output=True, text=True,
        timeout=180,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "cpcheck" in proc.stdout


# ------------------------------------------------------------ racecheck

def test_racecheck_detects_lock_order_cycle():
    rc = RaceCheck()
    l1, l2 = rc.lock("L1"), rc.lock("L2")

    def ab():
        with l1:
            with l2:
                pass

    def ba():
        with l2:
            with l1:
                pass

    for fn in (ab, ba):
        t = threading.Thread(target=fn, daemon=True)
        t.start()
        t.join(5)
    with pytest.raises(AssertionError, match="lock-order-cycle"):
        rc.assert_clean()
    kinds = {v.kind for v in rc.violations()}
    assert kinds == {"lock-order-cycle"}


def test_racecheck_consistent_order_is_clean():
    rc = RaceCheck()
    l1, l2 = rc.lock("L1"), rc.lock("L2")

    def ab():
        with l1:
            with l2:
                pass

    for _ in range(2):
        t = threading.Thread(target=ab, daemon=True)
        t.start()
        t.join(5)
    rc.assert_clean()


def test_racecheck_reentrant_rlock_no_self_edge():
    rc = RaceCheck()
    lock = rc.rlock("R")
    with lock:
        with lock:
            pass
    rc.assert_clean()


def test_racecheck_publish_while_held(run):
    from containerpilot_tpu.events import EventBus, GLOBAL_STARTUP

    async def scenario():
        rc = RaceCheck()
        bus = rc.wrap_bus(EventBus())
        table = rc.lock("replica-table")
        with table:
            bus.publish(GLOBAL_STARTUP)
        with pytest.raises(AssertionError, match="publish-while-held"):
            rc.assert_clean()
        rc.unwrap()
        # unwrapped: back to the plain publish
        bus.publish(GLOBAL_STARTUP)

    run(scenario())


def test_racecheck_publish_outside_lock_is_clean(run):
    from containerpilot_tpu.events import EventBus, GLOBAL_STARTUP

    async def scenario():
        with RaceCheck() as rc:
            bus = rc.wrap_bus(EventBus())
            table = rc.lock("replica-table")
            with table:
                pass
            bus.publish(GLOBAL_STARTUP)
        # context-manager exit ran assert_clean and unwrap

    run(scenario())


# ------------------------------------------------------------ loopcheck

def test_loopcheck_records_injected_stall(run):
    """A blocking call on the loop (the CP-ASYNCBLOCK failure shape)
    shows up in the lag ring as roughly its own duration."""
    import time

    from containerpilot_tpu.analysis import LoopLagProbe

    async def scenario():
        probe = LoopLagProbe(interval_s=0.01)
        probe.start()
        await asyncio.sleep(0.05)
        time.sleep(0.25)  # the injected stall, on the loop thread
        await asyncio.sleep(0.05)
        probe.stop()
        return probe

    probe = run(scenario())
    assert probe.max_ms() >= 150.0
    snap = probe.snapshot()
    assert snap["lag_max_ms"] == round(probe.max_ms(), 2)
    assert snap["heartbeats"] == probe.beats > 0


def test_loopcheck_clean_loop_reports_near_zero(run):
    """A loop doing nothing but sleeping schedules its heartbeats on
    time: p99 stays far under one stall's worth of lag."""
    from containerpilot_tpu.analysis import LoopLagProbe

    async def scenario():
        probe = LoopLagProbe(interval_s=0.01)
        probe.start()
        await asyncio.sleep(0.3)
        probe.stop()
        return probe

    probe = run(scenario())
    assert probe.beats >= 10
    assert probe.p99_ms() < 100.0  # ~0 in practice; CI-noise headroom


def test_loopcheck_probe_stop_is_idempotent(run):
    from containerpilot_tpu.analysis import LoopLagProbe

    async def scenario():
        probe = LoopLagProbe(interval_s=0.01)
        probe.start()
        probe.start()  # idempotent while running
        await asyncio.sleep(0.05)
        probe.stop()
        beats = probe.beats
        await asyncio.sleep(0.05)
        assert probe.beats == beats  # no heartbeat after stop
        probe.stop()

    run(scenario())


def test_loopcheck_watchdog_captures_leaked_exception(run):
    """A task that dies with nobody holding/awaiting it is recorded
    with its name; the loop keeps running."""
    from containerpilot_tpu.analysis import TaskWatchdog

    async def scenario():
        wd = TaskWatchdog(grace_s=0.01).install()

        async def boom():
            raise RuntimeError("kaput")

        task = asyncio.get_event_loop().create_task(
            boom(), name="leaky-relay"
        )
        del task  # fire-and-forget, the CP-TASKLEAK shape
        await asyncio.sleep(0.1)
        wd.uninstall()
        return wd

    wd = run(scenario())
    assert wd.tasks_created >= 1
    leaks = wd.snapshot()
    assert len(leaks) == 1
    assert leaks[0]["task"] == "leaky-relay"
    assert "kaput" in leaks[0]["exception"]


def test_loopcheck_watchdog_ignores_handled_and_cancelled(run):
    """An exception the awaiter catches is not a leak, and a
    cancelled task never is."""
    from containerpilot_tpu.analysis import TaskWatchdog

    async def scenario():
        wd = TaskWatchdog(grace_s=0.01).install()

        async def boom():
            raise ValueError("handled")

        try:
            await asyncio.get_event_loop().create_task(boom())
        except ValueError:
            pass

        async def forever():
            await asyncio.sleep(60)

        task = asyncio.get_event_loop().create_task(forever())
        task.cancel()
        await asyncio.sleep(0.1)
        wd.uninstall()
        # uninstall restores the previous factory
        assert asyncio.get_event_loop().get_task_factory() is None
        return wd

    wd = run(scenario())
    assert wd.snapshot() == []


def test_spawn_holds_reference_and_logs_death(run, caplog):
    """utils/tasks.spawn — the CP-TASKLEAK fix-in-a-call: the task is
    referenced (module pending set or the owner set) and a
    non-CancelledError death is logged immediately."""
    import logging

    from containerpilot_tpu.utils import tasks as task_util

    async def scenario():
        owned: set = set()

        async def ok():
            return 7

        async def boom():
            raise RuntimeError("spawned-death")

        t1 = task_util.spawn(ok(), name="ok-task", owner=owned)
        assert t1 in owned
        with caplog.at_level(logging.ERROR, "containerpilot.tasks"):
            task_util.spawn(boom(), name="doomed")
            assert task_util.pending_count() >= 1
            await asyncio.sleep(0.05)
        assert t1.result() == 7
        assert not owned  # done tasks leave their holder
        assert task_util.pending_count() == 0
        return [r.message for r in caplog.records]

    messages = run(scenario())
    assert any("doomed" in m and "spawned-death" in m for m in messages)
