"""Admission control + autoscaler tests: token buckets, bounded-queue
semantics (fast path, priority grant order, high-water/full sheds),
the deadline-expiry-means-zero-upstream-dispatch invariant, drain-rate-
derived Retry-After, the queued-load routing fold, gateway graceful
shutdown mid-traffic, and the autoscaler's hysteresis/cooldown/repair
decisions — all host-side, no JAX.
"""
import asyncio
import json
import time
import urllib.error
import urllib.request

import pytest

from containerpilot_tpu.discovery import (
    FileCatalogBackend,
    NoopBackend,
    ServiceRegistration,
)
from containerpilot_tpu.fleet import (
    AdmissionController,
    Autoscaler,
    AutoscalerConfig,
    DeadlineExpired,
    FleetGateway,
    FleetLoad,
    SessionLimited,
    ShedError,
)
from containerpilot_tpu.fleet.admission import (
    PRIORITY_BATCH,
    PRIORITY_INTERACTIVE,
    TokenBucket,
)
from containerpilot_tpu.fleet.gateway import Replica
from containerpilot_tpu.utils.http import HTTPServer, Response


def _post(port, path, payload, timeout=60, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read().decode(), dict(resp.headers)
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read().decode(), dict(exc.headers)


def _get(port, path, timeout=30):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout
        ) as resp:
            return resp.status, resp.read().decode(), dict(resp.headers)
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read().decode(), dict(exc.headers)


def _register(backend, instance_id, port, name="svc"):
    backend.service_register(
        ServiceRegistration(
            id=instance_id, name=name, port=port, ttl=60,
            address="127.0.0.1",
        ),
        status="passing",
    )


# -- token bucket (pure) ------------------------------------------------


def test_token_bucket_rate_and_refill():
    bucket = TokenBucket(rate=2.0, burst=2.0, now=0.0)
    assert bucket.take(0.0) is None
    assert bucket.take(0.0) is None
    wait = bucket.take(0.0)
    assert wait is not None and abs(wait - 0.5) < 1e-9
    # half a second refills one token at 2/s
    assert bucket.take(0.5) is None


# -- the controller's queue semantics -----------------------------------


def test_admission_fast_path_then_queue_then_grant(run):
    async def scenario():
        ctrl = AdmissionController(
            per_replica_inflight=2, max_queue_depth=4, high_water=2
        )
        ctrl.set_capacity(1)  # capacity 2
        t1 = await ctrl.admit()
        t2 = await ctrl.admit()
        assert ctrl.inflight == 2 and not t1.queued and not t2.queued
        waiter = asyncio.ensure_future(ctrl.admit())
        await asyncio.sleep(0)
        assert ctrl.depth == 1 and not waiter.done()
        ctrl.release(t1)
        t3 = await waiter
        assert t3.queued and ctrl.inflight == 2 and ctrl.depth == 0
        ctrl.release(t2)
        ctrl.release(t3)
        assert ctrl.inflight == 0
        assert ctrl.admitted == 3 and ctrl.queued_total == 1

    run(scenario(), timeout=30)


def test_priority_ordering_and_sheds_under_full_queue(run):
    """At the high-water mark batch sheds while interactive still
    queues; at the full mark everything sheds; grants drain the
    interactive class first."""

    async def scenario():
        ctrl = AdmissionController(
            per_replica_inflight=1, max_queue_depth=4, high_water=2
        )
        ctrl.set_capacity(1)  # capacity 1
        holder = await ctrl.admit()
        batch_waiter = asyncio.ensure_future(
            ctrl.admit(PRIORITY_BATCH)
        )
        await asyncio.sleep(0)
        inter_1 = asyncio.ensure_future(ctrl.admit())
        await asyncio.sleep(0)
        assert ctrl.depth == 2  # AT high water now
        with pytest.raises(ShedError) as shed:
            await ctrl.admit(PRIORITY_BATCH)
        assert shed.value.retry_after_s >= 1
        inter_2 = asyncio.ensure_future(ctrl.admit())
        await asyncio.sleep(0)
        inter_3 = asyncio.ensure_future(ctrl.admit())
        await asyncio.sleep(0)
        assert ctrl.depth == 4
        with pytest.raises(ShedError):
            await ctrl.admit()  # full queue sheds interactive too
        assert ctrl.shed_overload == 2
        # grants: all interactive before the batch waiter, FIFO
        # within a class — each release grants exactly the expected
        # waiter and no other
        pending = {
            "i1": inter_1, "i2": inter_2, "i3": inter_3,
            "b": batch_waiter,
        }
        for expected in ("i1", "i2", "i3", "b"):
            ctrl.release(holder)
            await asyncio.sleep(0)
            await asyncio.sleep(0)
            granted = [k for k, t in pending.items() if t.done()]
            assert granted == [expected], granted
            holder = (await pending.pop(expected))
        ctrl.release(holder)

    run(scenario(), timeout=30)


def test_deadline_expires_queued_request(run):
    async def scenario():
        ctrl = AdmissionController(
            per_replica_inflight=1, deadline_s=0.05
        )
        ctrl.set_capacity(1)
        holder = await ctrl.admit()
        t0 = time.monotonic()
        with pytest.raises(DeadlineExpired):
            await ctrl.admit()
        waited = time.monotonic() - t0
        assert 0.02 < waited < 2.0
        assert ctrl.expired == 1 and ctrl.depth == 0
        # the slot was never granted: releasing the holder leaves a
        # fully idle controller
        ctrl.release(holder)
        assert ctrl.inflight == 0

    run(scenario(), timeout=30)


def test_grant_racing_cancellation_leaks_no_slot(run):
    """A waiter granted in the same event-loop tick its task is
    cancelled must hand the slot back — otherwise a client hanging up
    at exactly the wrong moment leaks capacity forever."""

    async def scenario():
        ctrl = AdmissionController(per_replica_inflight=1)
        ctrl.set_capacity(1)
        holder = await ctrl.admit()
        waiter = asyncio.ensure_future(ctrl.admit())
        await asyncio.sleep(0)
        assert ctrl.depth == 1
        ctrl.release(holder)  # grants the waiter's future...
        waiter.cancel()  # ...in the same tick the task dies
        with pytest.raises(asyncio.CancelledError):
            await waiter
        assert ctrl.inflight == 0 and ctrl.depth == 0
        # capacity is genuinely back: a fresh admit is instant
        ctrl.release(await ctrl.admit())

    run(scenario(), timeout=30)


def test_session_bucket_raises_with_refill_hint(run):
    async def scenario():
        ctrl = AdmissionController(session_rate=1.0, session_burst=1.0)
        ctrl.set_capacity(4)
        t = await ctrl.admit(session="s1")
        ctrl.release(t)
        with pytest.raises(SessionLimited) as limited:
            await ctrl.admit(session="s1")
        assert limited.value.retry_after_s >= 1.0
        # other sessions are untouched
        ctrl.release(await ctrl.admit(session="s2"))
        assert ctrl.shed_session == 1

    run(scenario(), timeout=30)


def test_retry_after_tracks_observed_drain_rate():
    slow = AdmissionController()
    fast = AdmissionController()
    now = time.monotonic()
    # 3 completions over 4s -> ~0.5/s vs 40 over 4s -> ~10/s
    slow._completions.extend([now - 4, now - 2, now])  # noqa: SLF001
    fast._completions.extend(  # noqa: SLF001
        [now - 4 + i * 0.1 for i in range(41)]
    )
    slow.inflight = fast.inflight = 5  # the same backlog, both sides
    assert slow.retry_after_s() > fast.retry_after_s()
    assert fast.retry_after_s() >= 1  # floored delta-seconds


def test_drain_rate_decays_down_when_wedged_not_merely_idle():
    """Completions stopped WITH work pending = the fleet is stalling:
    the estimate must fall (long honest Retry-After), never jump back
    to capacity-optimism. Completions stopped with nothing pending is
    just a quiet gateway: the optimistic prior returns."""
    ctrl = AdmissionController(per_replica_inflight=64)
    ctrl.set_capacity(2)  # capacity 128
    now = time.monotonic()
    # was completing ~2/s, then everything stopped 5s ago
    stale = [now - 15 + i * 0.5 for i in range(21)]
    ctrl._completions.extend(stale)  # noqa: SLF001
    ctrl.inflight = 100  # backlog still out there: a wedge
    assert ctrl.drain_rate() < 2.0
    assert ctrl.retry_after_s() == 60  # clamped, not "2s, try again"
    ctrl.inflight = 0  # same stale window, but nothing pending
    assert ctrl.drain_rate() >= 128.0


def test_depth_one_queue_constructs_and_session_hint_is_capped(run):
    # max_queue_depth=1 must not crash on its own derived high_water
    ctrl = AdmissionController(max_queue_depth=1)
    assert ctrl.high_water == 1

    async def scenario():
        # a near-zero session rate quotes a capped Retry-After, not
        # an hour-scale one
        slow = AdmissionController(session_rate=0.01, session_burst=1.0)
        slow.set_capacity(4)
        slow.release(await slow.admit(session="s"))
        with pytest.raises(SessionLimited) as limited:
            await slow.admit(session="s")
        assert 1.0 <= limited.value.retry_after_s <= 60.0

    run(scenario(), timeout=30)


# -- routing folds queued load ------------------------------------------


def test_pick_counts_admission_queued_work():
    gw = FleetGateway(NoopBackend(), "svc")
    busy = Replica("aaa", "h", 1)
    busy.queued = 3  # sticky-pinned work waiting in the admission queue
    idle_looking = Replica("bbb", "h", 2)
    idle_looking.outstanding = 1
    gw._replicas = {"aaa": busy, "bbb": idle_looking}  # noqa: SLF001
    # only dispatched counts would pick aaa (0 outstanding); the
    # folded load signal knows aaa is absorbing queued work
    assert gw._pick().id == "bbb"  # noqa: SLF001


# -- gateway-level: deadline 504 with zero upstream dispatch ------------


def test_deadline_504_without_upstream_dispatch(run, tmp_path):
    backend = FileCatalogBackend(str(tmp_path))

    async def scenario():
        release = asyncio.Event()
        calls = [0]
        server = HTTPServer()

        async def handler(_req):
            calls[0] += 1
            await release.wait()
            return Response(200, b"{}", content_type="application/json")

        server.route("POST", "/v1/generate", handler)
        await server.start_tcp("127.0.0.1", 0)
        _register(backend, "aaa", server.bound_port)
        gw = FleetGateway(
            backend, "svc", "127.0.0.1", 0,
            poll_interval=0.05, hedge=False, retries=0,
            admission={"per_replica_inflight": 1, "deadline_s": 0.15},
        )
        await gw.run()
        loop = asyncio.get_event_loop()
        blocker = loop.run_in_executor(
            None, _post, gw.port, "/v1/generate", {"tokens": [[1]]},
        )
        for _ in range(100):
            if calls[0] == 1:
                break
            await asyncio.sleep(0.01)
        assert calls[0] == 1
        # the slot is held: this request queues, then dies at its
        # deadline WITHOUT the replica ever seeing it
        status, body, headers = await loop.run_in_executor(
            None, _post, gw.port, "/v1/generate", {"tokens": [[2]]},
        )
        assert status == 504, body
        assert {k.lower(): v for k, v in headers.items()}["retry-after"]
        assert calls[0] == 1, "expired request reached the replica"
        release.set()
        status, _, _ = await blocker
        assert status == 200
        # counters surfaced on /metrics and /fleet
        _, metrics, _ = await loop.run_in_executor(
            None, _get, gw.port, "/metrics"
        )
        assert "containerpilot_gateway_deadline_expired_total 1.0" in metrics
        assert "containerpilot_gateway_admission_depth" in metrics
        _, fleet, _ = await loop.run_in_executor(
            None, _get, gw.port, "/fleet"
        )
        snapshot = json.loads(fleet)
        assert snapshot["admission"]["deadline_expired"] == 1
        # the expired request was never admitted — only the blocker
        assert snapshot["admission"]["admitted"] == 1
        assert snapshot["draining"] is False
        await gw.stop()
        await server.stop()

    run(scenario(), timeout=60)


def test_batch_sheds_while_interactive_admitted_under_full_queue(
    run, tmp_path
):
    backend = FileCatalogBackend(str(tmp_path))

    async def scenario():
        release = asyncio.Event()
        server = HTTPServer()

        async def handler(_req):
            await release.wait()
            return Response(200, b"{}", content_type="application/json")

        server.route("POST", "/v1/generate", handler)
        await server.start_tcp("127.0.0.1", 0)
        _register(backend, "aaa", server.bound_port)
        gw = FleetGateway(
            backend, "svc", "127.0.0.1", 0,
            poll_interval=0.05, hedge=False, retries=0,
            admission={
                "per_replica_inflight": 1,
                "max_queue_depth": 4,
                "high_water": 1,
            },
        )
        await gw.run()
        loop = asyncio.get_event_loop()
        holder = loop.run_in_executor(
            None, _post, gw.port, "/v1/generate", {"tokens": [[1]]},
        )
        while gw.admission.inflight == 0:
            await asyncio.sleep(0.01)
        queued = loop.run_in_executor(
            None, _post, gw.port, "/v1/generate", {"tokens": [[2]]},
        )
        while gw.admission.depth == 0:
            await asyncio.sleep(0.01)
        # queue at high water: batch bounces fast with Retry-After,
        # interactive still gets in line
        status, body, headers = await loop.run_in_executor(
            None, _post, gw.port, "/v1/generate", {"tokens": [[3]]},
            60, {"X-Priority": "batch"},
        )
        assert status == 429, body
        assert {k.lower(): v for k, v in headers.items()}["retry-after"]
        interactive = loop.run_in_executor(
            None, _post, gw.port, "/v1/generate", {"tokens": [[4]]},
        )
        while gw.admission.depth < 2:
            await asyncio.sleep(0.01)
        release.set()
        for fut in (holder, queued, interactive):
            status, _, _ = await fut
            assert status == 200
        _, metrics, _ = await loop.run_in_executor(
            None, _get, gw.port, "/metrics"
        )
        assert (
            'containerpilot_gateway_shed_total'
            '{reason="high_water"} 1.0' in metrics
        )
        await gw.stop()
        await server.stop()

    run(scenario(), timeout=60)


# -- graceful shutdown ---------------------------------------------------


def test_gateway_graceful_drain_mid_traffic(run, tmp_path):
    """SIGTERM semantics: new work bounces 503 + Retry-After the
    moment drain starts, queued + in-flight requests all finish 200,
    and drain() returns True once idle."""
    backend = FileCatalogBackend(str(tmp_path))

    async def scenario():
        server = HTTPServer()

        async def handler(_req):
            await asyncio.sleep(0.15)
            return Response(200, b"{}", content_type="application/json")

        server.route("POST", "/v1/generate", handler)
        await server.start_tcp("127.0.0.1", 0)
        _register(backend, "aaa", server.bound_port)
        gw = FleetGateway(
            backend, "svc", "127.0.0.1", 0,
            poll_interval=0.05, hedge=False, retries=0,
            admission={"per_replica_inflight": 2},
        )
        await gw.run()
        loop = asyncio.get_event_loop()
        inflight = [
            loop.run_in_executor(
                None, _post, gw.port, "/v1/generate",
                {"tokens": [[i]]},
            )
            for i in range(4)
        ]
        while gw.admission.inflight + gw.admission.depth < 4:
            await asyncio.sleep(0.005)
        drainer = asyncio.ensure_future(gw.drain(timeout=10.0))
        await asyncio.sleep(0.01)
        # the gate is down for NEW work
        status, body, headers = await loop.run_in_executor(
            None, _post, gw.port, "/v1/generate", {"tokens": [[9]]},
        )
        assert status == 503 and b"draining" in body.encode()
        assert {k.lower(): v for k, v in headers.items()}["retry-after"]
        hstatus, _, _ = await loop.run_in_executor(
            None, _get, gw.port, "/health"
        )
        assert hstatus == 503
        # but everything already accepted lands
        for fut in inflight:
            status, _, _ = await fut
            assert status == 200
        assert await drainer is True
        assert gw.admission.inflight == 0 and gw.admission.depth == 0
        await gw.stop()
        await server.stop()

    run(scenario(), timeout=60)


# -- autoscaler decisions (fake launcher, manual clock) ------------------


class _FakeLauncher:
    def __init__(self, n):
        self._next = n
        self._ids = [f"r{i}" for i in range(n)]
        self.launches = 0
        self.retired = []

    def ids(self):
        return list(self._ids)

    def count(self):
        return len(self._ids)

    async def launch(self):
        rid = f"r{self._next}"
        self._next += 1
        self._ids.append(rid)
        self.launches += 1
        return rid

    async def retire(self, rid):
        self._ids.remove(rid)
        self.retired.append(rid)


def test_autoscaler_scale_up_needs_sustained_pressure_then_cools(run):
    async def scenario():
        launcher = _FakeLauncher(1)
        load = {"value": FleetLoad(queue_depth=6, per_replica={"r0": 2})}
        scaler = Autoscaler(
            launcher, lambda: load["value"],
            AutoscalerConfig(
                min_replicas=1, max_replicas=3, slots_per_replica=2,
                up_sustain_s=0.3, cooldown_s=100.0,
            ),
        )
        await scaler.tick(now=0.0)
        assert launcher.launches == 0  # pressure seen, not sustained
        await scaler.tick(now=0.1)
        assert launcher.launches == 0
        await scaler.tick(now=0.4)
        assert launcher.launches == 1 and launcher.count() == 2
        # still hot, but the cooldown holds a second launch
        await scaler.tick(now=0.8)
        await scaler.tick(now=1.5)
        assert launcher.launches == 1
        assert scaler.scale_ups == 1

    run(scenario(), timeout=30)


def test_autoscaler_scales_down_least_loaded_to_min(run):
    async def scenario():
        launcher = _FakeLauncher(3)
        load = {
            "value": FleetLoad(
                queue_depth=0,
                per_replica={"r0": 0.2, "r1": 0.0, "r2": 0.4},
            )
        }
        scaler = Autoscaler(
            launcher, lambda: load["value"],
            AutoscalerConfig(
                min_replicas=1, max_replicas=3, slots_per_replica=2,
                down_sustain_s=0.5, cooldown_s=0.0,
            ),
        )
        await scaler.tick(now=0.0)
        assert launcher.retired == []  # idle seen, not yet sustained
        await scaler.tick(now=0.6)
        assert launcher.retired == ["r1"]  # the idle one goes first
        # the sustain window restarts after each event
        await scaler.tick(now=1.3)
        assert launcher.retired == ["r1"]
        await scaler.tick(now=1.9)
        assert launcher.retired == ["r1", "r0"]
        # at min: idle forever changes nothing
        await scaler.tick(now=5.0)
        await scaler.tick(now=9.0)
        await scaler.tick(now=9.6)
        assert launcher.count() == 1 and scaler.scale_downs == 2

    run(scenario(), timeout=30)


def test_autoscaler_repairs_below_min_immediately(run):
    async def scenario():
        launcher = _FakeLauncher(1)
        scaler = Autoscaler(
            launcher,
            lambda: FleetLoad(queue_depth=0, per_replica={}),
            AutoscalerConfig(
                min_replicas=2, max_replicas=4, cooldown_s=0.0
            ),
        )
        # no pressure at all — min is an invariant, not a suggestion
        await scaler.tick(now=0.0)
        assert launcher.count() == 2 and scaler.scale_ups == 1

    run(scenario(), timeout=30)


class _FailingLauncher(_FakeLauncher):
    """launch() raises ``failures`` times before succeeding — the
    launcher-bug / replica-died-during-warmup shape."""

    def __init__(self, n, failures):
        super().__init__(n)
        self.failures = failures
        self.attempts = 0

    async def launch(self):
        self.attempts += 1
        if self.failures > 0:
            self.failures -= 1
            raise RuntimeError("replica died during warmup")
        return await super().launch()


def test_autoscaler_launch_failures_backoff_then_converge(run):
    """Three consecutive launch failures on the repair path: each is
    counted (launch_failures), no managed-count slot leaks, attempts
    are SPACED by the equal-jitter backoff (no per-tick storm), and
    the fleet still converges to min the moment launches heal — no
    thrash, exactly one successful launch."""

    async def scenario():
        launcher = _FailingLauncher(1, failures=3)
        scaler = Autoscaler(
            launcher,
            lambda: FleetLoad(queue_depth=0, per_replica={}),
            AutoscalerConfig(
                min_replicas=2, max_replicas=4, cooldown_s=0.0,
                launch_backoff_s=0.5, launch_backoff_cap_s=2.0,
                jitter_seed=7,
            ),
        )
        await scaler.tick(now=0.0)  # failure 1 arms the backoff
        assert scaler.launch_failures == 1
        assert launcher.count() == 1  # nothing leaked into managed
        # ticks inside the backoff window never attempt a launch —
        # the no-storm half (first delay is in [0.25, 0.5])
        await scaler.tick(now=0.05)
        await scaler.tick(now=0.15)
        assert launcher.attempts == 1
        await scaler.tick(now=1.0)   # failure 2 (backoff now 1.0)
        assert scaler.launch_failures == 2
        await scaler.tick(now=1.2)   # still inside [0.5, 1.0] delay
        assert launcher.attempts == 2
        await scaler.tick(now=3.0)   # failure 3 (backoff now 2.0)
        assert scaler.launch_failures == 3
        await scaler.tick(now=10.0)  # healed: repair lands
        assert launcher.count() == 2
        assert launcher.attempts == 4
        assert scaler.scale_ups == 1  # failures never counted as ups
        assert scaler.stats["launch_failures"] == 3
        # converged: further ticks change nothing
        await scaler.tick(now=11.0)
        await scaler.tick(now=12.0)
        assert launcher.count() == 2 and launcher.attempts == 4

    run(scenario(), timeout=30)


def test_autoscaler_stamps_launch_mode_from_standby_launcher(run):
    """A launcher exposing ``last_launch`` (the StandbyLauncher) gets
    its mode stamped into the scale log — the promoted/cold split the
    TTFRT report is judged on."""

    async def scenario():
        launcher = _FakeLauncher(1)
        launcher.last_launch = {"mode": "promoted", "replica": "r9"}
        scaler = Autoscaler(
            launcher,
            lambda: FleetLoad(queue_depth=0, per_replica={}),
            AutoscalerConfig(min_replicas=2, max_replicas=4),
        )
        await scaler.tick(now=0.0)  # repair: below min
        assert scaler.scale_log[-1]["mode"] == "promoted"

    run(scenario(), timeout=30)


def test_autoscaler_flapping_signal_causes_no_thrash(run):
    """A signal bouncing between hot and mid-band every tick (the
    shape a flapping catalog or bursty scrape produces) never sustains
    past the window, so the fleet size never moves."""

    async def scenario():
        launcher = _FakeLauncher(2)
        hot = FleetLoad(queue_depth=8, per_replica={"r0": 2, "r1": 2})
        mid = FleetLoad(queue_depth=0, per_replica={"r0": 1, "r1": 1})
        flip = {"n": 0}

        def signals():
            flip["n"] += 1
            return hot if flip["n"] % 2 else mid

        scaler = Autoscaler(
            launcher, signals,
            AutoscalerConfig(
                min_replicas=1, max_replicas=4, slots_per_replica=2,
                up_sustain_s=0.5, down_sustain_s=0.5, cooldown_s=0.1,
            ),
        )
        for i in range(20):
            await scaler.tick(now=i * 0.2)
        assert launcher.launches == 0 and launcher.retired == []

    run(scenario(), timeout=30)
