"""Cold-start collapse tests (fleet/standby.py + the serve seams):
weight-transfer wire roundtrip + resume + corruption fallback, the
standby role / promote-verb semantics (incl. the promote-racing-drain
race), warm-bucket marker skip, and the slow-boot chaos seam — tiny
model on the CPU backend, plus pure host-side units.
"""
import asyncio
import http.client
import json
import time
import urllib.error
import urllib.request

import pytest

from containerpilot_tpu.fleet.standby import (
    StandbyLauncher,
    WeightTransferError,
    fetch_params,
    fetch_weight_chunks,
    rebuild_params,
    weights_manifest,
)
from containerpilot_tpu.workload.modelcfg import (
    compile_cache_note,
    load_warm_buckets,
    mark_warm_buckets,
    parse_compile_cache_note,
    warmup_fingerprint,
)


def _get(port, path, timeout=30):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout
        ) as resp:
            return resp.status, resp.read(), dict(resp.headers)
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read(), dict(exc.headers)


def _post(port, path, payload=None, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload or {}).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read(), dict(resp.headers)
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read(), dict(exc.headers)


def _tiny_model():
    import jax
    import jax.numpy as jnp

    from containerpilot_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    return cfg, init_params(jax.random.PRNGKey(0), cfg)


def _server(cfg, params, **kwargs):
    from containerpilot_tpu.workload.serve import InferenceServer

    return InferenceServer(
        cfg, params, "127.0.0.1", 0, max_len=64,
        slots=2, slot_chunk=4, **kwargs,
    )


# -- weight wire (pure) -------------------------------------------------


def test_weights_manifest_rebuild_roundtrip():
    """Serialize -> chunk -> rebuild is byte-identical, and the
    manifest's accounting (total bytes, per-chunk digests) is
    self-consistent with small chunks forcing multi-chunk leaves."""
    import jax
    import numpy as np

    from containerpilot_tpu.fleet.standby import (
        _chunk_digest,
        leaf_bytes,
    )

    cfg, params = _tiny_model()
    manifest = weights_manifest(params, chunk_bytes=1000)
    leaves = jax.tree_util.tree_leaves(params)
    assert len(manifest["leaves"]) == len(leaves)
    assert manifest["total_bytes"] == sum(
        np.asarray(leaf).nbytes for leaf in leaves
    )
    # some leaf must span multiple chunks at this chunk size
    owners = [c["leaf"] for c in manifest["chunks"]]
    assert any(owners.count(i) > 1 for i in set(owners))
    # materialize the chunk bytes the way the server does
    chunks = []
    for spec in manifest["chunks"]:
        data = leaf_bytes(leaves[spec["leaf"]])
        piece = data[spec["offset"]:spec["offset"] + spec["len"]]
        assert _chunk_digest(piece) == spec["digest"]
        chunks.append(piece)
    like = jax.tree_util.tree_map(np.zeros_like, params)
    rebuilt = rebuild_params(manifest, chunks, like)
    for a, b in zip(leaves, jax.tree_util.tree_leaves(rebuilt)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_rebuild_rejects_structural_mismatch():
    import numpy as np

    cfg, params = _tiny_model()
    manifest = weights_manifest(params)
    import jax

    leaves = jax.tree_util.tree_leaves(params)
    chunks = []
    for spec in manifest["chunks"]:
        data = np.asarray(leaves[spec["leaf"]]).tobytes()
        chunks.append(
            data[spec["offset"]:spec["offset"] + spec["len"]]
        )
    # wrong leaf count
    with pytest.raises(WeightTransferError):
        rebuild_params(manifest, chunks, {"just_one": leaves[0]})
    # wrong shape in `like`
    bad = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(params),
        [np.zeros((3, 3), np.float32) for _ in leaves],
    )
    with pytest.raises(WeightTransferError):
        rebuild_params(manifest, chunks, bad)


# -- the live transfer (mux) --------------------------------------------


def test_fetch_params_over_mux_and_resume_endpoint(run):
    """End to end against a live replica: fetch_params returns a
    byte-identical tree over cp-mux/1, and ``?chunk=K`` re-serves
    exactly the suffix (the resume contract a mid-transfer redial
    relies on)."""
    import jax
    import numpy as np

    cfg, params = _tiny_model()

    async def scenario():
        server = _server(cfg, params)
        await server.run()
        like = jax.tree_util.tree_map(np.zeros_like, params)
        fetched = await fetch_params("127.0.0.1", server.port, like)
        assert fetched is not None
        for a, b in zip(
            jax.tree_util.tree_leaves(params),
            jax.tree_util.tree_leaves(fetched),
        ):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        # the resume surface: a plain keep-alive read of ?chunk=K
        # yields manifest + exactly the chunk suffix
        manifest, chunks = await fetch_weight_chunks(
            "127.0.0.1", server.port
        )
        resume_at = len(chunks) - 2
        loop = asyncio.get_event_loop()

        def read_stream():
            conn = http.client.HTTPConnection(
                "127.0.0.1", server.port, timeout=30
            )
            try:
                conn.request(
                    "GET", f"/v1/weights?chunk={resume_at}"
                )
                resp = conn.getresponse()
                assert resp.status == 200
                return resp.read()
            finally:
                conn.close()

        raw = await loop.run_in_executor(None, read_stream)
        mlen = int.from_bytes(raw[:8], "big")
        assert json.loads(raw[8:8 + mlen]) == manifest
        assert raw[8 + mlen:] == b"".join(chunks[resume_at:])
        await server.stop()

    run(scenario(), timeout=300)


def test_fetch_params_corruption_falls_back_to_none(run):
    """A digest mismatch (peer reloaded/bit-rot) is NOT retried: the
    fetch returns None and the caller takes the disk/init path."""
    cfg, params = _tiny_model()

    async def scenario():
        server = _server(cfg, params)
        await server.run()
        # poison one advertised digest AFTER the manifest caches: the
        # served bytes recompute honestly and can never match it
        await server._ensure_weights_manifest()  # noqa: SLF001
        manifest = server._weights_manifest_cache  # noqa: SLF001
        manifest["chunks"][0]["digest"] = "0" * 16
        from containerpilot_tpu.fleet.standby import encode_manifest

        server._weights_manifest_bytes = (  # noqa: SLF001
            encode_manifest(manifest)
        )
        fetched = await fetch_params("127.0.0.1", server.port, params)
        assert fetched is None
        await server.stop()

    run(scenario(), timeout=300)


# -- standby role + promote verb ----------------------------------------


def test_standby_role_health_refusal_and_promote_verb(run):
    """A warm standby: /health 503 standby, generate refused 503,
    score/model reads stay up; POST /v3/standby/promote flips it in
    one call (second promote 409s — the exactly-one-winner half the
    replica enforces); generate then serves."""
    cfg, params = _tiny_model()

    async def scenario():
        loop = asyncio.get_event_loop()
        server = _server(cfg, params, role="standby")
        await server.run()
        body = {"tokens": [[1, 2, 3]], "max_new_tokens": 4}
        health = await loop.run_in_executor(
            None, _get, server.port, "/health"
        )
        refused = await loop.run_in_executor(
            None, _post, server.port, "/v1/generate", body
        )
        score = await loop.run_in_executor(
            None, _post, server.port, "/v1/score",
            {"tokens": [[1, 2, 3, 4]]},
        )
        first = await loop.run_in_executor(
            None, _post, server.port, "/v3/standby/promote"
        )
        second = await loop.run_in_executor(
            None, _post, server.port, "/v3/standby/promote"
        )
        served = await loop.run_in_executor(
            None, _post, server.port, "/v1/generate", body
        )
        health_after = await loop.run_in_executor(
            None, _get, server.port, "/health"
        )
        await server.stop()
        return health, refused, score, first, second, served, health_after

    health, refused, score, first, second, served, health_after = run(
        scenario(), timeout=300
    )
    assert health[0] == 503 and b"standby" in health[1]
    assert refused[0] == 503 and b"standby" in refused[1]
    assert {k.lower(): v for k, v in refused[2].items()}["retry-after"]
    assert score[0] == 200
    assert first[0] == 200 and json.loads(first[1])["promoted"]
    assert second[0] == 409
    assert served[0] == 200
    assert health_after[0] == 200


def test_promote_racing_drain_409s_until_resume(run):
    """Promote racing drain: a DRAINING standby refuses promotion
    (409) — capacity leaving the fleet must not be promoted into it —
    and promotes cleanly once maintenance exits."""
    cfg, params = _tiny_model()

    async def scenario():
        loop = asyncio.get_event_loop()
        server = _server(cfg, params, role="standby")
        await server.run()
        server.enter_maintenance()
        refused = await loop.run_in_executor(
            None, _post, server.port, "/v3/standby/promote"
        )
        assert not server.promote()  # the in-process verb agrees
        server.exit_maintenance()
        accepted = await loop.run_in_executor(
            None, _post, server.port, "/v3/standby/promote"
        )
        await server.stop()
        return refused, accepted

    refused, accepted = run(scenario(), timeout=300)
    assert refused[0] == 409 and b"draining" in refused[1]
    assert accepted[0] == 200


# -- warm-bucket marker + warmup skip -----------------------------------


def test_warm_bucket_marker_roundtrip_and_tolerance(tmp_path):
    cfg, _ = _tiny_model()
    fp = warmup_fingerprint(cfg, 64, slots=2, slot_chunk=4)
    other = warmup_fingerprint(cfg, 128, slots=2, slot_chunk=4)
    assert fp != other  # max_len shapes the program set
    assert load_warm_buckets(str(tmp_path), fp) == set()
    mark_warm_buckets(str(tmp_path), fp, {"p4"})
    mark_warm_buckets(str(tmp_path), fp, {"p16", "slots"})
    assert load_warm_buckets(str(tmp_path), fp) == {
        "p4", "p16", "slots"
    }
    assert load_warm_buckets(str(tmp_path), other) == set()
    # garbage marker: tolerant empty read, and marking heals it
    (tmp_path / "cp_warm_buckets.json").write_text("{not json")
    assert load_warm_buckets(str(tmp_path), fp) == set()
    mark_warm_buckets(str(tmp_path), fp, {"p4"})
    assert load_warm_buckets(str(tmp_path), fp) == {"p4"}
    # the cc= advertisement VALUE roundtrips through the tolerant
    # parser (the "cc=" name itself is owned by fleet/notes.py)
    note = compile_cache_note(str(tmp_path))
    assert ":" in note and " " not in note
    digest, cache_dir = parse_compile_cache_note(note)
    assert digest and cache_dir == str(tmp_path)
    assert parse_compile_cache_note("garbage") == ("", "")
    assert parse_compile_cache_note(None) == ("", "")
    assert compile_cache_note("") == ""


def test_warmup_skips_marked_buckets(run, tmp_path, monkeypatch):
    """Two same-shaped servers sharing a compile cache dir: the first
    warms and marks; the second's warmup drives ZERO decode compiles
    (the marker skip — its compile_warmup seconds collapse, which is
    the cold-start lever the shared cache exists for)."""
    import jax

    from containerpilot_tpu.models import decode as decode_mod
    from containerpilot_tpu.workload.serve import InferenceServer

    cfg, params = _tiny_model()
    calls = {"n": 0}
    real_generate = decode_mod.generate

    def counting_generate(*args, **kwargs):
        calls["n"] += 1
        return real_generate(*args, **kwargs)

    monkeypatch.setattr(decode_mod, "generate", counting_generate)
    # the server ENABLES its cache dir at construction (the marker
    # must never promise executables the disk cache doesn't hold);
    # restore the suite's per-user cache afterwards so later tests
    # don't write compiles into this test's doomed tmpdir
    prev_cache = jax.config.jax_compilation_cache_dir

    async def scenario():
        first = InferenceServer(
            cfg, params, "127.0.0.1", 0, max_len=64,
            compile_cache_dir=str(tmp_path),
        )
        await first.run()
        await first.stop()
        after_first = calls["n"]
        assert after_first > 0
        second = InferenceServer(
            cfg, params, "127.0.0.1", 0, max_len=64,
            compile_cache_dir=str(tmp_path),
        )
        await second.run()
        await second.stop()
        assert calls["n"] == after_first  # every bucket skipped
        assert second.ready
        # the cc= advertisement was computed once at warmup end
        _digest, adv_dir = parse_compile_cache_note(
            second.compile_cache_note()
        )
        assert adv_dir == str(tmp_path)

    try:
        run(scenario(), timeout=300)
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_cache)


def test_slow_boot_hook_parks_warmup_as_compile_badput(run):
    """The chaos_hook("warmup") seam: an injected slow boot delays
    ready AND lands in the ledger's compile_warmup stage — the
    cold-start badput the standby pool masks."""
    cfg, params = _tiny_model()

    async def scenario():
        server = _server(cfg, params)

        async def hook(endpoint):
            if endpoint == "warmup":
                await asyncio.sleep(0.4)

        server.chaos_hook = hook
        t0 = time.monotonic()
        await server.run()
        boot_s = time.monotonic() - t0
        totals = server.ledger.totals()
        await server.stop()
        assert boot_s >= 0.4
        assert totals["compile_warmup"] >= 0.4

    run(scenario(), timeout=300)


# -- StandbyLauncher units (pure asyncio) --------------------------------


class _FakeStandbyInner:
    """Programmable inner launcher for StandbyLauncher units."""

    def __init__(self):
        self._next = 0
        self._active = []
        self.standbys = {}  # id -> alive
        self.promote_calls = []
        self.standby_failures = 0  # launch_standby raises this many times

    def ids(self):
        return list(self._active)

    def count(self):
        return len(self._active)

    async def launch(self):
        rid = f"cold-{self._next}"
        self._next += 1
        self._active.append(rid)
        return rid

    async def retire(self, rid):
        self._active.remove(rid)

    async def launch_standby(self):
        if self.standby_failures > 0:
            self.standby_failures -= 1
            raise RuntimeError("standby crashed mid-boot")
        rid = f"sb-{self._next}"
        self._next += 1
        self.standbys[rid] = True
        return rid

    async def promote(self, rid):
        self.promote_calls.append(rid)
        await asyncio.sleep(0)  # a real promote awaits the wire
        if not self.standbys.get(rid, False):
            return False
        del self.standbys[rid]
        self._active.append(rid)
        return True


def test_standby_launcher_promotes_then_refills(run):
    async def scenario():
        inner = _FakeStandbyInner()
        pool = StandbyLauncher(inner, standby_count=1,
                               refill_backoff=0.01)
        await pool.prefill()
        assert len(pool.standby_ids()) == 1
        rid = await pool.launch()
        assert rid.startswith("sb-") and rid in inner.ids()
        assert pool.promotions == 1 and pool.cold_launches == 0
        assert pool.last_launch["mode"] == "promoted"
        for _ in range(100):
            if len(pool.standby_ids()) == 1:
                break
            await asyncio.sleep(0.01)
        assert len(pool.standby_ids()) == 1  # background refill landed
        await pool.stop()

    run(scenario(), timeout=30)


def test_standby_launcher_promote_race_single_winner(run):
    """Two concurrent launches against a one-standby pool: exactly
    one promotes it (claimed before any await), the other cold-
    launches — the standby is never promoted twice."""

    async def scenario():
        inner = _FakeStandbyInner()
        pool = StandbyLauncher(inner, standby_count=1,
                               refill_backoff=0.01)
        await pool.prefill()
        first, second = await asyncio.gather(
            pool.launch(), pool.launch()
        )
        modes = sorted(
            rid.split("-")[0] for rid in (first, second)
        )
        assert modes == ["cold", "sb"]
        assert pool.promotions == 1 and pool.cold_launches == 1
        # the standby saw exactly ONE promote call
        sb = [rid for rid in (first, second) if rid.startswith("sb-")]
        assert inner.promote_calls.count(sb[0]) == 1
        await pool.stop()

    run(scenario(), timeout=30)


def test_standby_launcher_dead_standby_falls_back_cold(run):
    """A standby that died between pooling and promotion is dropped
    (promote -> False) and the launch proceeds — next standby or the
    cold path — without surfacing an error."""

    async def scenario():
        inner = _FakeStandbyInner()
        pool = StandbyLauncher(inner, standby_count=1,
                               refill_backoff=0.01)
        await pool.prefill()
        dead = pool.standby_ids()[0]
        inner.standbys[dead] = False  # crashed in the pool
        rid = await pool.launch()
        assert rid.startswith("cold-")
        assert pool.promote_failures == 1
        assert pool.last_launch["mode"] == "cold"
        await pool.stop()

    run(scenario(), timeout=30)


def test_standby_crash_mid_refill_retries_with_backoff(run):
    """launch_standby raising mid-refill counts a failure and the
    loop retries (equal-jitter backoff) until the pool converges —
    a crashing standby boot never strands the pool empty."""

    async def scenario():
        inner = _FakeStandbyInner()
        inner.standby_failures = 2  # first two boots crash
        pool = StandbyLauncher(
            inner, standby_count=1,
            refill_backoff=0.01, refill_backoff_cap=0.02,
        )
        pool._ensure_refill()  # noqa: SLF001 — the background path
        for _ in range(200):
            if len(pool.standby_ids()) == 1:
                break
            await asyncio.sleep(0.01)
        assert len(pool.standby_ids()) == 1
        assert pool.refill_failures == 2
        await pool.stop()

    run(scenario(), timeout=30)
