"""Container-image integration scenarios, mirroring the reference's
docker-based tiers (reference: scripts/test.sh:50-140,
integration_tests/tests/test_reap_zombies, test_sigterm). Skipped when
no docker daemon is available (the reference's integration tier is
likewise a separate make target gated on docker)."""
import json
import shutil
import subprocess
import uuid

import pytest

pytestmark = pytest.mark.skipif(
    shutil.which("docker") is None, reason="docker not available"
)

IMAGE = "containerpilot-tpu:test"


@pytest.fixture(scope="module")
def image():
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    build = subprocess.run(
        ["docker", "build", "-q", "-t", IMAGE, repo],
        capture_output=True, text=True, timeout=600,
    )
    if build.returncode != 0:
        pytest.skip(f"docker build failed: {build.stderr[-500:]}")
    return IMAGE


def _run(image, config: dict, timeout: int = 60, extra=()):
    name = f"cpt-test-{uuid.uuid4().hex[:8]}"
    cmd = [
        "docker", "run", "--rm", "--name", name, *extra,
        "-e", f"CONTAINERPILOT_CONFIG_JSON={json.dumps(config)}",
        "--entrypoint", "/bin/sh", image, "-c",
        'echo "$CONTAINERPILOT_CONFIG_JSON" > /etc/containerpilot.json5 '
        "&& exec /bin/cpsup python -m containerpilot_tpu "
        "-config /etc/containerpilot.json5",
    ]
    return subprocess.run(cmd, capture_output=True, text=True, timeout=timeout)


def test_image_runs_all_jobs_complete(image):
    """The supervisor under cpsup runs a one-shot job and exits 0 when
    every job is complete (reference: test_no_command behavior)."""
    cfg = {
        "jobs": [{"name": "hello", "exec": ["/bin/echo", "hello-from-image"]}]
    }
    proc = _run(image, cfg)
    assert proc.returncode == 0, proc.stderr[-500:]
    assert "hello-from-image" in proc.stdout + proc.stderr


def test_image_reaps_zombies(image):
    """Orphaned grandchildren must be reaped by cpsup as PID 1
    (reference: test_reap_zombies/run.sh:14-36)."""
    # the job double-forks orphans, then a second job inspects the
    # process table: no more than one transient zombie allowed
    cfg = {
        "jobs": [
            {
                "name": "orphaner",
                "exec": [
                    "/bin/sh", "-c",
                    "for i in 1 2 3; do (sleep 0.1 &) ; done; sleep 1",
                ],
            },
            {
                "name": "checker",
                "when": {"source": "orphaner", "once": "stopped"},
                "exec": [
                    "/bin/sh", "-c",
                    "sleep 2; z=$(ls /proc | grep -c '^[0-9]' || true); "
                    "echo procs=$z",
                ],
            },
        ]
    }
    proc = _run(image, cfg, timeout=90)
    assert proc.returncode == 0, proc.stderr[-500:]
    assert "procs=" in proc.stdout + proc.stderr
