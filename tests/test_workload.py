"""TPU-workload tests on the virtual 8-device CPU mesh: model numerics,
pallas kernel parity, sharded train step, graft entry points."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from containerpilot_tpu.models.transformer import (
    TransformerConfig,
    forward,
    init_params,
    loss_fn,
)
from containerpilot_tpu.ops.attention import (
    causal_attention,
    flash_attention_forward,
)
from containerpilot_tpu.parallel import (
    MeshPlan,
    init_train_state,
    make_mesh,
    make_train_step,
)


CFG = TransformerConfig(
    vocab_size=128, d_model=64, n_heads=2, n_layers=2, d_ff=128,
    max_seq_len=64,
)


def test_forward_shapes_and_finiteness():
    params = init_params(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 16), 0, CFG.vocab_size, jnp.int32
    )
    logits = jax.jit(lambda p, t: forward(p, t, CFG))(params, tokens)
    assert logits.shape == (2, 16, CFG.vocab_size)
    assert bool(jnp.isfinite(logits).all())


def test_loss_decreases_under_training():
    """Overfit a single tiny batch: loss must drop substantially."""
    mesh = make_mesh(jax.devices()[:1], plan=MeshPlan(1, 1))
    state = init_train_state(jax.random.PRNGKey(0), CFG, mesh,
                             learning_rate=1e-2)
    step = make_train_step(CFG, mesh, learning_rate=1e-2)
    tokens = jax.random.randint(
        jax.random.PRNGKey(2), (2, 33), 0, CFG.vocab_size, jnp.int32
    )
    first = None
    for _ in range(10):
        state, loss = step(state, tokens)
        if first is None:
            first = float(loss)
    assert float(loss) < first * 0.8, (first, float(loss))


def test_causality():
    """Changing future tokens must not change past logits."""
    params = init_params(jax.random.PRNGKey(0), CFG)
    t1 = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, 128, jnp.int32)
    t2 = t1.at[0, 10:].set((t1[0, 10:] + 1) % 128)
    l1 = forward(params, t1, CFG)
    l2 = forward(params, t2, CFG)
    np.testing.assert_allclose(
        np.asarray(l1[0, :10]), np.asarray(l2[0, :10]), rtol=1e-4, atol=1e-4
    )


def test_flash_attention_matches_xla():
    """The pallas kernel (interpret mode on CPU) must match the einsum
    reference."""
    rng = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(rng, 3)
    shape = (2, 256, 2, 64)  # [batch, seq, heads, head_dim]
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    ref = causal_attention(q, k, v)
    flash = flash_attention_forward(q, k, v, block_q=128, block_k=128)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(flash), rtol=2e-3, atol=2e-3
    )


def test_flash_attention_rejects_ragged_seq():
    q = jnp.zeros((1, 100, 2, 64))
    with pytest.raises(ValueError, match="not a multiple"):
        flash_attention_forward(q, q, q)


def test_flash_attention_grad_parity():
    """The pallas backward kernels (dq, dk/dv) must match jax.grad
    through the einsum reference."""
    from containerpilot_tpu.ops.flash import flash_attention

    rng = jax.random.PRNGKey(3)
    kq, kk, kv, kc = jax.random.split(rng, 4)
    shape = (2, 256, 2, 64)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    cot = jax.random.normal(kc, shape, jnp.float32)

    with jax.default_matmul_precision("float32"):
        g_ref = jax.grad(
            lambda q, k, v: jnp.sum(causal_attention(q, k, v) * cot),
            argnums=(0, 1, 2),
        )(q, k, v)
        g_fl = jax.grad(
            lambda q, k, v: jnp.sum(flash_attention(q, k, v, 64, 64) * cot),
            argnums=(0, 1, 2),
        )(q, k, v)
    for ref, fl in zip(g_ref, g_fl):
        np.testing.assert_allclose(
            np.asarray(ref), np.asarray(fl), rtol=2e-3, atol=2e-3
        )


def test_flash_attention_mismatched_block_sizes():
    """block_q != block_k exercises the rows-fully-masked-in-this-block
    paths of the online softmax and both backward kernels."""
    from containerpilot_tpu.ops.flash import flash_attention

    rng = jax.random.PRNGKey(4)
    kq, kk, kv, kc = jax.random.split(rng, 4)
    shape = (1, 256, 2, 64)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    cot = jax.random.normal(kc, shape, jnp.float32)
    with jax.default_matmul_precision("float32"):
        ref = causal_attention(q, k, v)
        g_ref = jax.grad(
            lambda q, k, v: jnp.sum(causal_attention(q, k, v) * cot),
            argnums=(0, 1, 2),
        )(q, k, v)
        for bq, bk in [(128, 64), (64, 128)]:
            out = flash_attention(q, k, v, bq, bk)
            np.testing.assert_allclose(
                np.asarray(ref), np.asarray(out), rtol=2e-3, atol=2e-3
            )
            g_fl = jax.grad(
                lambda q, k, v: jnp.sum(
                    flash_attention(q, k, v, bq, bk) * cot
                ),
                argnums=(0, 1, 2),
            )(q, k, v)
            for r, f in zip(g_ref, g_fl):
                np.testing.assert_allclose(
                    np.asarray(r), np.asarray(f), rtol=2e-3, atol=2e-3
                )


def test_flash_auto_select_threshold():
    """TransformerConfig auto-picks flash at/after flash_min_seq."""
    from containerpilot_tpu.models.transformer import flash_eligible

    cfg = TransformerConfig(flash_min_seq=1024)
    assert not flash_eligible(cfg, 512)
    assert flash_eligible(cfg, 1024)
    assert flash_eligible(cfg, 4096)
    assert not flash_eligible(cfg, 1100)  # not 128-aligned
    assert not flash_eligible(TransformerConfig(flash_min_seq=0), 4096)


def test_training_through_auto_flash_matches_causal():
    """A train step whose seq length crosses flash_min_seq runs the
    pallas fwd+bwd kernels; the loss must match the einsum path."""
    cfg_flash = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=2, n_layers=1, d_ff=128,
        max_seq_len=128, flash_min_seq=128,
    )
    cfg_causal = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=2, n_layers=1, d_ff=128,
        max_seq_len=128, flash_min_seq=0,
    )
    params = init_params(jax.random.PRNGKey(0), cfg_flash)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 129), 0, 128, jnp.int32
    )
    with jax.default_matmul_precision("float32"):
        l_flash, g_flash = jax.value_and_grad(loss_fn)(
            params, tokens, cfg_flash
        )
        l_causal, g_causal = jax.value_and_grad(loss_fn)(
            params, tokens, cfg_causal
        )
    np.testing.assert_allclose(
        float(l_flash), float(l_causal), rtol=1e-2
    )
    flat_f = jax.tree_util.tree_leaves(g_flash)
    flat_c = jax.tree_util.tree_leaves(g_causal)
    for f, c in zip(flat_f, flat_c):
        np.testing.assert_allclose(
            np.asarray(f), np.asarray(c), rtol=5e-2, atol=5e-3
        )


def test_sharded_train_step_flash_shard_map():
    """dp x tp training where the seq length triggers the shard_map
    flash path (pallas under manual partitioning)."""
    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=2, n_layers=1, d_ff=128,
        max_seq_len=128, flash_min_seq=128,
    )
    mesh = make_mesh(jax.devices()[:4], plan=MeshPlan(2, 2))
    state = init_train_state(jax.random.PRNGKey(0), cfg, mesh)
    step = make_train_step(cfg, mesh)
    tokens = jax.random.randint(
        jax.random.PRNGKey(2), (4, 129), 0, 128, jnp.int32
    )
    state, loss = step(state, tokens)
    assert bool(jnp.isfinite(loss))


def test_mesh_factorization():
    mesh = make_mesh(jax.devices()[:8])
    assert mesh.axis_names == ("data", "model")
    assert mesh.devices.shape == (2, 4)
    mesh1 = make_mesh(jax.devices()[:1])
    assert mesh1.devices.shape == (1, 1)
    with pytest.raises(ValueError):
        make_mesh(jax.devices()[:8], plan=MeshPlan(3, 2))


def test_sharded_train_step_8_devices():
    """The full tp x dp train step over the virtual 8-device mesh."""
    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq_len=64,
    )  # heads/ff/vocab divisible by the 4-way model axis
    mesh = make_mesh(jax.devices()[:8])
    state = init_train_state(jax.random.PRNGKey(0), cfg, mesh)
    step = make_train_step(cfg, mesh)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (4, 33), 0, cfg.vocab_size, jnp.int32
    )
    state, loss = step(state, tokens)
    assert bool(jnp.isfinite(loss))
    assert int(state.step) == 1
    # params actually sharded: wq's model axis split over 4 devices
    wq_sharding = state.params["layers"]["wq"].sharding
    assert len(wq_sharding.device_set) == 8


def test_lr_schedule_shapes():
    """Warmup ramps from 0, cosine decays to the floor, constant stays
    a plain float (state layout unchanged for existing checkpoints)."""
    from containerpilot_tpu.parallel import make_optimizer
    from containerpilot_tpu.parallel.train import lr_schedule

    assert lr_schedule(3e-4) == 3e-4
    warm = lr_schedule(1e-3, warmup_steps=10)
    assert float(warm(0)) == 0.0
    np.testing.assert_allclose(float(warm(5)), 5e-4, rtol=1e-6)
    np.testing.assert_allclose(float(warm(10)), 1e-3, rtol=1e-6)
    np.testing.assert_allclose(float(warm(1000)), 1e-3, rtol=1e-6)
    full = lr_schedule(1e-3, warmup_steps=10, decay_steps=90)
    np.testing.assert_allclose(float(full(10)), 1e-3, rtol=1e-6)
    # halfway through decay: midpoint of peak and floor
    np.testing.assert_allclose(float(full(55)), 5.5e-4, rtol=1e-3)
    np.testing.assert_allclose(float(full(100)), 1e-4, rtol=1e-3)
    np.testing.assert_allclose(float(full(500)), 1e-4, rtol=1e-3)
    # a scheduled optimizer still initializes and updates
    opt = make_optimizer(1e-3, warmup_steps=2, decay_steps=4)
    params = {"w": jnp.ones((4,))}
    opt_state = opt.init(params)
    updates, _ = opt.update(
        {"w": jnp.full((4,), 0.5)}, opt_state, params
    )
    assert updates["w"].shape == (4,)


def test_grad_accumulation_matches_full_batch():
    """accum_steps=2 must produce the same loss and parameter update as
    the single-shot step on the same batch (equal-size chunks: mean of
    chunk means == full-batch mean)."""
    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq_len=64, dtype=jnp.float32,
    )
    mesh = make_mesh(jax.devices()[:8])
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size, jnp.int32
    )
    state_a = init_train_state(jax.random.PRNGKey(0), cfg, mesh)
    state_b = init_train_state(jax.random.PRNGKey(0), cfg, mesh)
    step_full = make_train_step(cfg, mesh)
    step_accum = make_train_step(cfg, mesh, accum_steps=2)
    state_a, loss_a = step_full(state_a, tokens)
    state_b, loss_b = step_accum(state_b, tokens)
    np.testing.assert_allclose(
        float(loss_a), float(loss_b), rtol=1e-5, atol=1e-6
    )
    flat_a = jax.tree_util.tree_leaves(state_a.params)
    flat_b = jax.tree_util.tree_leaves(state_b.params)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
        )
    with pytest.raises(ValueError, match="not divisible"):
        step3 = make_train_step(cfg, mesh, accum_steps=3)
        step3(init_train_state(jax.random.PRNGKey(0), cfg, mesh), tokens)


def test_zero1_shards_moments_and_matches_plain_step():
    """ZeRO-1: adam mu/nu shard over the data axis (per-device moment
    memory drops by the dp factor) and the update stays numerically
    equivalent to the replicated-optimizer step."""
    from containerpilot_tpu.parallel.train import train_state_shardings

    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq_len=64, dtype=jnp.float32,
    )
    mesh = make_mesh(jax.devices()[:8])  # data=2, model=4
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (4, 33), 0, cfg.vocab_size, jnp.int32
    )

    plain = init_train_state(jax.random.PRNGKey(0), cfg, mesh)
    z1 = init_train_state(jax.random.PRNGKey(0), cfg, mesh, zero1=True)

    # the moments really are sharded over data: wq's mu gains a "data"
    # axis, and each device holds half of it
    mu_plain = plain.opt_state[1][0].mu["layers"]["wq"]
    mu_z1 = z1.opt_state[1][0].mu["layers"]["wq"]
    assert "data" in mu_z1.sharding.spec
    assert "data" not in (mu_plain.sharding.spec or ())
    shard_elems = lambda a: a.addressable_shards[0].data.size
    assert shard_elems(mu_z1) * 2 == shard_elems(mu_plain)

    # the canonical shardings agree with what init produced (pinned
    # in_shardings would otherwise reshard silently)
    shardings = train_state_shardings(cfg, mesh, zero1=True)
    assert shardings.opt_state[1][0].mu["layers"]["wq"] == mu_z1.sharding

    step_plain = make_train_step(cfg, mesh)
    step_z1 = make_train_step(cfg, mesh, zero1=True)
    plain, loss_a = step_plain(plain, tokens)
    z1, loss_b = step_z1(z1, tokens)
    np.testing.assert_allclose(
        float(loss_a), float(loss_b), rtol=1e-6, atol=1e-7
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(plain.params),
        jax.tree_util.tree_leaves(z1.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )


def test_graft_entry_points():
    import __graft_entry__ as graft

    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[-1] == 256
    graft.dryrun_multichip(8)


def test_ring_attention_matches_single_device():
    """Context-parallel ring attention over a 4-way seq axis must match
    single-device causal attention exactly in structure and closely in
    numerics."""
    from containerpilot_tpu.ops import ring_attention
    from containerpilot_tpu.parallel import MeshPlan, make_mesh

    mesh = make_mesh(jax.devices()[:8], plan=MeshPlan(data=2, model=1, seq=4))
    assert mesh.axis_names == ("data", "seq", "model")
    rng = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(rng, 3)
    shape = (2, 128, 2, 32)  # [batch, seq, heads, head_dim]
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    ref = causal_attention(q, k, v)
    ring = ring_attention(q, k, v, mesh)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(ring), rtol=2e-4, atol=2e-4
    )


def test_cp_generate_matches_unsharded(run):
    """Context-parallel serving prefill: a long prompt sharded over
    an 8-way seq axis rings through prefill, the cache gathers once,
    and the decode produces the same tokens the unsharded path does —
    greedy and with the sampling knobs riding along."""
    from containerpilot_tpu.models.decode import generate
    from containerpilot_tpu.models.transformer import init_params
    from containerpilot_tpu.parallel import (
        MeshPlan,
        cp_generate,
        make_mesh,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_kv_heads=2,
        n_layers=2, d_ff=64, max_seq_len=128, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh(
        jax.devices()[:8], plan=MeshPlan(data=1, model=1, seq=8)
    )
    prompt = jax.random.randint(
        jax.random.PRNGKey(3), (1, 64), 0, cfg.vocab_size, jnp.int32
    )

    plain = generate(params, prompt, cfg, 8, 128)
    cp = cp_generate(params, prompt, cfg, mesh, 8, 128)
    assert [int(t) for t in cp[0]] == [int(t) for t in plain[0]]

    # the sampling contract rides unchanged (seeded + logit_bias)
    rng = jnp.stack([jax.random.fold_in(jax.random.PRNGKey(5), 0)])
    kw = dict(temperature=0.9, top_k=12, rng=rng,
              logit_bias={7: -100.0})
    plain_s = generate(params, prompt, cfg, 8, 128, **kw)
    cp_s = cp_generate(params, prompt, cfg, mesh, 8, 128, **kw)
    assert [int(t) for t in cp_s[0]] == [int(t) for t in plain_s[0]]
    assert 7 not in [int(t) for t in cp_s[0]]

    # a non-axis-divisible prompt: the divisible head rings, the
    # remainder extends the gathered cache — still byte-equal
    odd = jax.random.randint(
        jax.random.PRNGKey(9), (1, 30), 0, cfg.vocab_size, jnp.int32
    )
    plain_odd = generate(params, odd, cfg, 6, 128)
    cp_odd = cp_generate(params, odd, cfg, mesh, 6, 128)
    assert [int(t) for t in cp_odd[0]] == [int(t) for t in plain_odd[0]]

    # int8 KV cache composes: the ring reads the dequant roundtrip in
    # prefill and the gathered cache carries the scales
    import dataclasses as _dc

    cfg_q = _dc.replace(cfg, kv_int8=True)
    plain_q = generate(params, prompt, cfg_q, 6, 128)
    cp_q = cp_generate(params, prompt, cfg_q, mesh, 6, 128)
    assert [int(t) for t in cp_q[0]] == [int(t) for t in plain_q[0]]

    # cp x tp: model-sharded params on a (seq, model) mesh — the ring
    # keeps heads on 'model' inside its shard_map, the gathered cache
    # decodes tensor-parallel, output still byte-equal
    from containerpilot_tpu.parallel import shard_params

    mesh_tp = make_mesh(
        jax.devices()[:8], plan=MeshPlan(data=1, model=2, seq=4)
    )
    sharded = shard_params(params, mesh_tp, cfg)
    cp_tp = cp_generate(sharded, prompt, cfg, mesh_tp, 8, 128)
    assert [int(t) for t in cp_tp[0]] == [int(t) for t in plain[0]]

    # contract checks fail loudly
    with pytest.raises(ValueError, match="shorter than"):
        cp_generate(params, jnp.ones((1, 6), jnp.int32), cfg, mesh,
                    4, 128)
    with pytest.raises(ValueError, match="exceeds max_len"):
        cp_generate(params, prompt, cfg, mesh, 128, 128)
    no_seq = make_mesh(jax.devices()[:8], plan=MeshPlan(data=1, model=8))
    with pytest.raises(ValueError, match="no 'seq' axis"):
        cp_generate(params, prompt, cfg, no_seq, 4, 128)


def test_cp_remainder_extend_steps_are_capped(monkeypatch):
    """The bucketed-head remainder must extend in pieces no larger
    than max(axis, prefill_chunk): a pod bucket can leave a remainder
    just under head tokens, and an uncapped power-of-two step would
    run one chunk-x-cache attention far above the ring's per-device
    activation bound — the worst case --sp advertises protection
    against (ADVICE r5). Host-only: the ring head and the extend
    program are stubbed so just the decomposition runs, and the piece
    set stays the finite {2^k <= cap} + tails that keeps the pod's
    compile-skew story intact."""
    import containerpilot_tpu.models.decode as dec
    from containerpilot_tpu.parallel import MeshPlan, make_mesh
    from containerpilot_tpu.parallel import context as ctx

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=128, dtype=jnp.float32,
    )
    mesh = make_mesh(
        jax.devices()[:2], plan=MeshPlan(data=1, model=1, seq=2)
    )
    monkeypatch.setattr(
        ctx, "_cp_prefill_fn",
        lambda *a: lambda params, sharded: ("logits", {}),
    )
    widths = []

    def fake_extend(_cfg):
        def ext(params, cache, chunk):
            widths.append(int(chunk.shape[1]))
            return "logits", cache

        return ext

    monkeypatch.setattr(dec, "_jitted_extend", fake_extend)
    prompt = np.zeros((1, 39), np.int32)
    # head 8 leaves a 31-token remainder — the uncapped decomposition
    # would run a single 16-wide piece even with --prefill-chunk 8
    for prefill_chunk, cap in ((8, 8), (0, 2)):
        widths.clear()
        ctx.cp_prefill_with_remainder(
            None, prompt, cfg, mesh, 128, head=8,
            prefill_chunk=prefill_chunk,
        )
        assert sum(widths) == 39 - 8, widths
        assert max(widths) <= cap, widths


@pytest.mark.parametrize(
    "plan_kw", [dict(model=1, seq=8), dict(model=2, seq=4)],
    ids=["cp8", "cp4xtp2"],
)
def test_serve_cp_long_prompt_matches_vanilla(run, plan_kw):
    """--cp end-to-end: a server with a seq-axis mesh (pure, or
    composed with tensor parallelism — model-sharded params on a
    seq x model mesh) answers long prompts byte-identically to a
    vanilla server, short prompts take the normal path, and
    /v1/model reports the cp config; bad compositions fail at
    construction."""
    import json
    import urllib.request

    from containerpilot_tpu.models.transformer import init_params
    from containerpilot_tpu.parallel import (
        MeshPlan,
        make_mesh,
        shard_params,
    )
    from containerpilot_tpu.workload.serve import InferenceServer

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_kv_heads=2,
        n_layers=2, d_ff=64, max_seq_len=128, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh(
        jax.devices()[:8], plan=MeshPlan(data=1, **plan_kw)
    )
    srv_params = (
        shard_params(params, mesh, cfg)
        if plan_kw["model"] > 1 else params
    )
    cp_srv = InferenceServer(
        cfg, srv_params, "127.0.0.1", 0, max_len=128, cp_mesh=mesh,
        cp_min_len=32,
    )
    vanilla = InferenceServer(cfg, params, "127.0.0.1", 0, max_len=128)

    # --cp composes with --slots: the engine rings long-prompt
    # admissions over the seq axis (the pod's --sp recipe), so a
    # slot-pooled server answers long prompts identically too
    slot_cp_srv = InferenceServer(
        cfg, srv_params, "127.0.0.1", 0, max_len=128, cp_mesh=mesh,
        cp_min_len=32, slots=2,
    )
    # an explicit threshold no admissible prompt can reach fails at
    # startup; the DERIVED default instead self-clamps below max_len
    with pytest.raises(ValueError, match="never engages"):
        InferenceServer(
            cfg, params, "127.0.0.1", 0, max_len=128, cp_mesh=mesh,
            cp_min_len=128,
        )
    defaulted = InferenceServer(
        cfg, params, "127.0.0.1", 0, max_len=32, cp_mesh=mesh,
    )
    assert defaulted.cp_min_len == 31  # min(8*8, max_len-1)

    import numpy as _np

    long_prompt = _np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=45
    ).tolist()

    def fetch(port, body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/generate",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            return json.loads(resp.read().decode())

    async def scenario():
        import asyncio

        await cp_srv.run()
        await vanilla.run()
        await slot_cp_srv.run()
        loop = asyncio.get_event_loop()

        def go():
            reqs = [
                {"tokens": [long_prompt], "max_new_tokens": 6},
                {"tokens": [long_prompt], "max_new_tokens": 5,
                 "temperature": 0.8, "top_k": 10, "seed": 4},
                {"tokens": [[1, 2, 3]], "max_new_tokens": 4},  # short
            ]
            pairs = [
                (fetch(cp_srv.port, r), fetch(vanilla.port, r),
                 fetch(slot_cp_srv.port, r))
                for r in reqs
            ]
            info = urllib.request.urlopen(
                f"http://127.0.0.1:{cp_srv.port}/v1/model", timeout=30
            ).read().decode()
            return pairs, json.loads(info)

        out = await loop.run_in_executor(None, go)
        await cp_srv.stop()
        await vanilla.stop()
        await slot_cp_srv.stop()
        return out

    pairs, info = run(scenario(), timeout=300)
    for got, want, slot_got in pairs:
        assert got["tokens"] == want["tokens"]
        # the slot-pooled cp server answers identically (engine
        # admissions ring the same maximal head cp_generate uses)
        assert slot_got["tokens"] == want["tokens"]
    assert info["cp"] == {"seq": plan_kw["seq"], "min_len": 32}


def test_ring_attention_gqa_native():
    """The ring rotates unrepeated (grouped) kv heads and must match
    repeat_kv + single-device attention."""
    from containerpilot_tpu.models.transformer import repeat_kv as rep
    from containerpilot_tpu.ops import ring_attention
    from containerpilot_tpu.parallel import MeshPlan, make_mesh

    mesh = make_mesh(jax.devices()[:8], plan=MeshPlan(data=2, model=1, seq=4))
    rng = jax.random.PRNGKey(6)
    kq, kk, kv = jax.random.split(rng, 3)
    b, s, h, kvh, hd = 2, 128, 4, 2, 32
    q = jax.random.normal(kq, (b, s, h, hd), jnp.float32)
    k = jax.random.normal(kk, (b, s, kvh, hd), jnp.float32)
    v = jax.random.normal(kv, (b, s, kvh, hd), jnp.float32)
    with jax.default_matmul_precision("float32"):
        ref = causal_attention(q, rep(k, h), rep(v, h))
        ring = jax.jit(
            lambda q, k, v: ring_attention(q, k, v, mesh)
        )(q, k, v)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(ring), rtol=2e-4, atol=2e-4
    )
    with pytest.raises(ValueError, match="divide"):
        ring_attention(q, k[:, :, :0], v[:, :, :0], mesh)


def test_ring_attention_mqa_fallback_on_tp_axis():
    """MQA (1 kv head) with a >1 tp axis: grouped heads can't shard
    over model, so the ring falls back to rotating full heads — and
    must still be exact."""
    from containerpilot_tpu.models.transformer import repeat_kv as rep
    from containerpilot_tpu.ops import ring_attention
    from containerpilot_tpu.parallel import MeshPlan, make_mesh

    mesh = make_mesh(jax.devices()[:8], plan=MeshPlan(data=2, model=2, seq=2))
    rng = jax.random.PRNGKey(9)
    kq, kk, kv = jax.random.split(rng, 3)
    b, s, h, kvh, hd = 2, 64, 4, 1, 32
    q = jax.random.normal(kq, (b, s, h, hd), jnp.float32)
    k = jax.random.normal(kk, (b, s, kvh, hd), jnp.float32)
    v = jax.random.normal(kv, (b, s, kvh, hd), jnp.float32)
    with jax.default_matmul_precision("float32"):
        ref = causal_attention(q, rep(k, h), rep(v, h))
        ring = jax.jit(
            lambda q, k, v: ring_attention(q, k, v, mesh)
        )(q, k, v)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(ring), rtol=2e-4, atol=2e-4
    )


def test_gqa_context_parallel_train_step():
    """dp x sp x tp with a GQA model: the ring gets the unrepeated kv
    (gqa_native contract) and the loss matches the 2D-mesh step."""
    from containerpilot_tpu.parallel import context_parallel_config

    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2, n_layers=2,
        d_ff=128, max_seq_len=64,
    )
    tokens = jax.random.randint(
        jax.random.PRNGKey(8), (4, 65), 0, cfg.vocab_size, jnp.int32
    )
    mesh2 = make_mesh(jax.devices()[:8], plan=MeshPlan(data=4, model=2))
    state2 = init_train_state(jax.random.PRNGKey(0), cfg, mesh2)
    _, loss2 = make_train_step(cfg, mesh2)(state2, tokens)
    mesh3 = make_mesh(
        jax.devices()[:8], plan=MeshPlan(data=2, seq=2, model=2)
    )
    cfg3 = context_parallel_config(cfg, mesh3)
    assert getattr(cfg3.attention_fn, "gqa_native", False)
    state3 = init_train_state(jax.random.PRNGKey(0), cfg3, mesh3)
    _, loss3 = make_train_step(cfg3, mesh3)(state3, tokens)
    assert bool(jnp.isfinite(loss3))
    np.testing.assert_allclose(float(loss2), float(loss3), rtol=5e-3)


def test_ring_attention_validates_inputs():
    from containerpilot_tpu.ops import ring_attention
    from containerpilot_tpu.parallel import MeshPlan, make_mesh

    mesh2d = make_mesh(jax.devices()[:8])  # no seq axis
    q = jnp.zeros((1, 64, 2, 16))
    with pytest.raises(ValueError, match="no 'seq' axis"):
        ring_attention(q, q, q, mesh2d)
    mesh3d = make_mesh(jax.devices()[:8], plan=MeshPlan(2, 1, 4))
    q_ragged = jnp.zeros((1, 66, 2, 16))
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention(q_ragged, q_ragged, q_ragged, mesh3d)


def test_context_parallel_train_step():
    """Full dp x sp x tp train step with ring attention inside the
    model: loss must match the XLA-attention step closely."""
    from containerpilot_tpu.parallel import context_parallel_config

    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq_len=64,
    )
    tokens = jax.random.randint(
        jax.random.PRNGKey(7), (4, 65), 0, cfg.vocab_size, jnp.int32
    )
    # reference: plain 2D mesh step
    mesh2 = make_mesh(jax.devices()[:8], plan=MeshPlan(data=4, model=2))
    state2 = init_train_state(jax.random.PRNGKey(0), cfg, mesh2)
    _, loss2 = make_train_step(cfg, mesh2)(state2, tokens)
    # context-parallel: 3D mesh, ring attention in the forward
    mesh3 = make_mesh(
        jax.devices()[:8], plan=MeshPlan(data=2, seq=2, model=2)
    )
    cfg3 = context_parallel_config(cfg, mesh3)
    state3 = init_train_state(jax.random.PRNGKey(0), cfg3, mesh3)
    _, loss3 = make_train_step(cfg3, mesh3)(state3, tokens)
    assert bool(jnp.isfinite(loss3))
    np.testing.assert_allclose(
        float(loss2), float(loss3), rtol=5e-3
    )


def test_restore_params_from_scheduled_checkpoint(tmp_path):
    """A checkpoint written under an lr-scheduled optimizer (extra
    count state in the opt tree) must still open with the serving
    path's default skeleton: the opt_state placeholder structure comes
    from the checkpoint's own metadata, not the caller."""
    from containerpilot_tpu.parallel import (
        abstract_train_state,
        make_optimizer,
        restore_params,
        save_checkpoint,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    mesh = make_mesh(jax.devices()[:1])
    opt = make_optimizer(1e-3, warmup_steps=2, decay_steps=10)
    state = init_train_state(
        jax.random.PRNGKey(0), cfg, mesh, optimizer=opt
    )
    step = make_train_step(cfg, mesh, optimizer=opt)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size, jnp.int32
    )
    state, _ = step(state, tokens)
    save_checkpoint(str(tmp_path), 1, state)

    # the serving process knows nothing of the training schedule
    abstract = abstract_train_state(jax.random.PRNGKey(0), cfg, mesh)
    params, restored_step = restore_params(str(tmp_path), abstract)
    assert int(restored_step) == 1
    np.testing.assert_allclose(
        np.asarray(params["embed"]),
        np.asarray(state.params["embed"]),
        rtol=1e-6,
    )


def test_checkpoint_save_restore_roundtrip(tmp_path):
    """Crash-resume: save a sharded TrainState, restore into a fresh
    one, training state carries over."""
    from containerpilot_tpu.parallel import (
        latest_step,
        restore_checkpoint,
        save_checkpoint,
    )

    mesh = make_mesh(jax.devices()[:8])
    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq_len=64,
    )
    state = init_train_state(jax.random.PRNGKey(0), cfg, mesh)
    step = make_train_step(cfg, mesh)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (4, 33), 0, cfg.vocab_size, jnp.int32
    )
    state, _ = step(state, tokens)
    state, _ = step(state, tokens)
    ckdir = str(tmp_path / "ckpts")
    save_checkpoint(ckdir, 2, state)
    assert latest_step(ckdir) == 2

    fresh = init_train_state(jax.random.PRNGKey(0), cfg, mesh)
    restored = restore_checkpoint(ckdir, fresh)
    assert restored is not None
    assert int(restored.step) == 2
    np.testing.assert_allclose(
        np.asarray(state.params["norm_out"]),
        np.asarray(restored.params["norm_out"]),
    )
    # restored state is usable: one more step runs
    restored, loss = step(restored, tokens)
    assert bool(jnp.isfinite(loss))
    assert restore_checkpoint(str(tmp_path / "nope"), fresh) is None
    # pruning keeps only the newest `keep` checkpoints
    save_checkpoint(ckdir, 3, restored, keep=1)
    assert latest_step(ckdir) == 3
    import os

    assert sorted(os.listdir(ckdir)) == ["step_3"]


def test_restored_params_pickles_and_deepcopies():
    """RestoredParams crosses process boundaries (serving restores in
    executors); tuple.__getnewargs__ must supply all three ctor args."""
    import copy
    import pickle

    from containerpilot_tpu.parallel.checkpoint import RestoredParams

    r = RestoredParams({"w": 1}, 5, True)
    params, step = r  # stays a 2-tuple for existing unpack sites
    assert (params, step) == ({"w": 1}, 5)
    for clone in (pickle.loads(pickle.dumps(r)), copy.deepcopy(r)):
        assert tuple(clone) == tuple(r) and clone.ema is True


def test_restore_params_only(tmp_path):
    """Serving restore: params (and step) come back; optimizer moments
    stay orbax PLACEHOLDERs and are never materialized."""
    from containerpilot_tpu.parallel import (
        abstract_train_state,
        restore_params,
        save_checkpoint,
    )

    mesh = make_mesh(jax.devices()[:8])
    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq_len=64,
    )
    state = init_train_state(jax.random.PRNGKey(0), cfg, mesh)
    step = make_train_step(cfg, mesh)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (4, 33), 0, cfg.vocab_size, jnp.int32
    )
    state, _ = step(state, tokens)
    ckdir = str(tmp_path / "ckpts")
    save_checkpoint(ckdir, 1, state)

    abstract = abstract_train_state(jax.random.PRNGKey(0), cfg, mesh)
    params, ck_step = restore_params(ckdir, abstract)
    assert int(ck_step) == 1
    np.testing.assert_allclose(
        np.asarray(state.params["norm_out"]), np.asarray(params["norm_out"])
    )
    # the restored params serve a forward directly
    logits = forward(params, tokens[:, :8], cfg)
    assert bool(jnp.isfinite(logits).all())
    assert restore_params(str(tmp_path / "nope"), abstract) is None


def test_prefill_through_flash_matches_forward():
    """A flash-eligible prompt length routes prefill through the pallas
    kernels; last-position logits must equal the full forward."""
    from containerpilot_tpu.models.decode import prefill

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=256, dtype=jnp.float32, flash_min_seq=128,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 128), 0, cfg.vocab_size, jnp.int32
    )
    with jax.default_matmul_precision("float32"):
        ref = forward(params, tokens, cfg)[:, -1, :]
        logits, cache = prefill(params, tokens, cfg, max_len=256)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(logits), rtol=2e-3, atol=2e-3
    )
    assert int(cache["pos"]) == 128


def test_flash_forward_gqa_native():
    """flash_attention_forward reads unrepeated kv heads (GQA) and
    must match the repeat_kv + einsum reference."""
    rng = jax.random.PRNGKey(5)
    kq, kk, kv = jax.random.split(rng, 3)
    b, s, h, kvh, hd = 2, 256, 4, 2, 64
    q = jax.random.normal(kq, (b, s, h, hd), jnp.float32)
    k = jax.random.normal(kk, (b, s, kvh, hd), jnp.float32)
    v = jax.random.normal(kv, (b, s, kvh, hd), jnp.float32)
    from containerpilot_tpu.models.transformer import repeat_kv as rep

    with jax.default_matmul_precision("float32"):
        ref = causal_attention(q, rep(k, h), rep(v, h))
        out = flash_attention_forward(q, k, v, block_q=64, block_k=64)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(out), rtol=2e-3, atol=2e-3
    )
    with pytest.raises(ValueError, match="dividing"):
        # 3 kv heads don't divide 4 query heads
        kk3 = jnp.concatenate([k, k[:, :, :1]], axis=2)
        flash_attention_forward(q, kk3, kk3, 64, 64)
    with pytest.raises(ValueError, match="incompatible"):
        # cache-shaped kv longer than the prompt must be rejected, not
        # silently truncated
        k2 = jnp.concatenate([k, k], axis=1)
        flash_attention_forward(q, k2, k2, 64, 64)
    with pytest.raises(ValueError, match="incompatible"):
        flash_attention_forward(q, k[:1], v[:1], 64, 64)  # batch mismatch
    with pytest.raises(ValueError, match="incompatible"):
        flash_attention_forward(q, k[:, :, :0], v[:, :, :0], 64, 64)

    # the differentiable path must refuse unrepeated GQA kv — its
    # backward would return wrong-shaped dk/dv
    from containerpilot_tpu.ops.flash import flash_attention

    with pytest.raises(ValueError, match="full-head"):
        flash_attention(q, k, v, 64, 64)


def test_gqa_prefill_through_flash_matches_forward():
    """A GQA model's flash-eligible prefill (unrepeated kv through the
    kernel) must match the full forward."""
    from containerpilot_tpu.models.decode import prefill

    cfg = TransformerConfig(
        vocab_size=64, d_model=64, n_heads=4, n_kv_heads=2, n_layers=1,
        d_ff=64, max_seq_len=256, dtype=jnp.float32, flash_min_seq=128,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 128), 0, cfg.vocab_size, jnp.int32
    )
    with jax.default_matmul_precision("float32"):
        ref = forward(params, tokens, cfg)[:, -1, :]
        logits, _cache = prefill(params, tokens, cfg, max_len=256)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(logits), rtol=2e-3, atol=2e-3
    )


def test_incremental_decode_matches_full_forward():
    """Prefill + decode_step logits must equal the full forward's
    per-position logits (teacher forcing)."""
    from containerpilot_tpu.models.decode import decode_step, prefill

    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=2, n_layers=2, d_ff=128,
        max_seq_len=32, dtype=jnp.float32,  # f32 for tight comparison
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 12), 0, cfg.vocab_size, jnp.int32
    )
    full = forward(params, tokens, cfg)  # [b, 12, vocab]

    # prefill on the first 6, then feed the rest one at a time
    logits, cache = prefill(params, tokens[:, :6], cfg, max_len=16)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full[:, 5]), rtol=2e-4, atol=2e-4
    )
    for i in range(6, 12):
        logits, cache = decode_step(params, cache, tokens[:, i], cfg)
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(full[:, i]), rtol=2e-4, atol=2e-4,
            err_msg=f"position {i}",
        )


def test_generate_greedy_deterministic():
    from containerpilot_tpu.models.decode import generate

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (1, 4), 0, 64, jnp.int32
    )
    out1 = generate(params, prompt, cfg, max_new_tokens=8, max_len=16)
    out2 = generate(params, prompt, cfg, max_new_tokens=8, max_len=16)
    assert out1.shape == (1, 8)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    assert int(out1.min()) >= 0 and int(out1.max()) < 64


def test_sample_logits_top_k_top_p():
    """top-k keeps the k best ids; top-p keeps the minimal nucleus."""
    from containerpilot_tpu.models.decode import sample_logits

    # id 3 is the mode (p ~ 0.64 at temp 1), then 2, 1, 0, 4
    logits = jnp.tile(
        jnp.asarray([[0.0, 1.0, 2.0, 3.0, -1.0]], jnp.float32), (512, 1)
    )
    one = jnp.float32(1.0)
    key = jax.random.PRNGKey(7)
    top2 = sample_logits(logits, key, one, top_k=2)
    assert set(np.asarray(top2).tolist()) <= {2, 3}
    # top_k=1 is greedy regardless of the key
    top1 = sample_logits(logits, jax.random.PRNGKey(8), one, top_k=1)
    assert set(np.asarray(top1).tolist()) == {3}
    # nucleus 0.5: the mode alone already covers the mass
    nucleus = sample_logits(logits, key, one, top_p=0.5)
    assert set(np.asarray(nucleus).tolist()) == {3}
    # nucleus 0.9 needs {3, 2, 1}; id 0 and 4 stay excluded
    wide = sample_logits(logits, key, one, top_p=0.9)
    assert set(np.asarray(wide).tolist()) <= {1, 2, 3}
    # unfiltered sampling can reach every id
    free = sample_logits(logits, key, jnp.float32(3.0))
    assert len(set(np.asarray(free).tolist())) >= 4


def test_generate_sampling_modes_and_eos():
    from containerpilot_tpu.models.decode import generate

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (2, 4), 0, 64, jnp.int32
    )
    greedy = generate(params, prompt, cfg, max_new_tokens=6, max_len=16)
    # temperature ~0 with top_k=1 reproduces greedy
    t1 = generate(
        params, prompt, cfg, max_new_tokens=6, max_len=16,
        temperature=0.5, top_k=1,
    )
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(t1))
    # sampled output stays in-vocab
    sampled = generate(
        params, prompt, cfg, max_new_tokens=6, max_len=16,
        temperature=1.0, top_k=8, top_p=0.9,
    )
    assert int(sampled.min()) >= 0 and int(sampled.max()) < 64

    # eos early-stop: make the first greedy token the eos — the rest of
    # the row must be pad
    eos = int(greedy[0, 0])
    stopped = generate(
        params, prompt, cfg, max_new_tokens=6, max_len=16,
        eos_id=eos, pad_id=63,
    )
    row = np.asarray(stopped[0]).tolist()
    first_eos = row.index(eos)
    assert all(t == 63 for t in row[first_eos + 1:])

    with pytest.raises(ValueError, match="top_k"):
        generate(params, prompt, cfg, max_new_tokens=2, max_len=16,
                 top_p=1.5)


def test_decode_chunk_matches_decode_steps():
    """The multi-token incremental step (speculative verify) must be
    numerically equivalent to sequential single-token steps."""
    from containerpilot_tpu.models.decode import (
        decode_chunk, decode_step, prefill,
    )

    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=2, n_layers=2, d_ff=128,
        max_seq_len=32, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 12), 0, cfg.vocab_size, jnp.int32
    )
    _logits, cache_a = prefill(params, tokens[:, :6], cfg, max_len=16)
    _logits, cache_b = prefill(params, tokens[:, :6], cfg, max_len=16)
    chunk_logits, cache_a = decode_chunk(params, cache_a, tokens[:, 6:12], cfg)
    for i in range(6):
        step_logits, cache_b = decode_step(params, cache_b, tokens[:, 6 + i], cfg)
        np.testing.assert_allclose(
            np.asarray(chunk_logits[:, i]), np.asarray(step_logits),
            rtol=2e-4, atol=2e-4, err_msg=f"chunk position {i}",
        )
    assert int(cache_a["pos"]) == int(cache_b["pos"]) == 12
    np.testing.assert_allclose(
        np.asarray(cache_a["k"]), np.asarray(cache_b["k"]),
        rtol=1e-5, atol=1e-5,
    )


def test_speculative_matches_vanilla_greedy():
    """Speculative decoding must reproduce the target's greedy output
    EXACTLY for any draft — the draft changes speed, never content."""
    from containerpilot_tpu.models.decode import generate
    from containerpilot_tpu.models.speculative import (
        layer_prefix_draft, speculative_generate,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=3, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (1, 5), 0, 64, jnp.int32
    )
    want = generate(params, prompt, cfg, max_new_tokens=20, max_len=40)

    # weak draft: 1-layer prefix
    dparams, dcfg = layer_prefix_draft(params, cfg, 1)
    got, stats = speculative_generate(
        params, dparams, prompt, cfg, dcfg,
        max_new_tokens=20, max_len=40, speculate=4,
    )
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))
    assert stats["tokens"] == 20 and stats["rounds"] >= 5

    # perfect draft (the target itself): every round fully accepts
    got2, stats2 = speculative_generate(
        params, params, prompt, cfg, cfg,
        max_new_tokens=20, max_len=40, speculate=4,
    )
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got2))
    # token 1 comes from prefill; a perfect draft fully accepts every
    # round, emitting k+1 = 5 per round (4 drafts + the bonus token):
    # 19 remaining tokens take ceil(19/5) = 4 verify rounds
    assert stats2["rounds"] == 4
    assert stats2["accepted_drafts"] == 16

    with pytest.raises(ValueError, match="batch 1"):
        speculative_generate(
            params, dparams, jnp.ones((2, 3), jnp.int32), cfg, dcfg,
            max_new_tokens=4, max_len=40,
        )
    with pytest.raises(ValueError, match="draft layers"):
        layer_prefix_draft(params, cfg, 3)

    # eos early-exit: pick the greedy row's 3rd token as "eos" — the
    # spec loop must stop paying rounds once a round emits it, and the
    # prefix through that token must still match vanilla greedy exactly
    want_row = np.asarray(want)[0].tolist()
    eos = want_row[2]
    cut = want_row.index(eos) + 1  # first occurrence may be earlier
    got3, stats3 = speculative_generate(
        params, dparams, prompt, cfg, dcfg,
        max_new_tokens=20, max_len=40, speculate=4, eos_id=eos,
    )
    row3 = np.asarray(got3)[0].tolist()
    assert eos in row3 and row3.index(eos) == cut - 1
    assert row3[:cut] == want_row[:cut]
    assert stats3["tokens"] < 20  # stopped early, not padded to max
    assert stats3["rounds"] < stats["rounds"]


def test_inference_server_end_to_end(run):
    """The serving path: warmup -> health -> generate over HTTP."""
    import urllib.request

    from containerpilot_tpu.models.transformer import init_params
    from containerpilot_tpu.workload.serve import InferenceServer

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,  # tight score-parity check
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    server = InferenceServer(cfg, params, "127.0.0.1", 0, max_len=32)

    def fetch(path, body=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}{path}",
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"} if body else {},
        )
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return resp.status, resp.read().decode()
        except urllib.error.HTTPError as exc:
            return exc.code, exc.read().decode()

    async def scenario():
        import asyncio

        await server.run()  # includes warmup
        loop = asyncio.get_event_loop()
        health = await loop.run_in_executor(None, fetch, "/health")
        gen = await loop.run_in_executor(
            None,
            lambda: fetch(
                "/v1/generate",
                {"tokens": [[1, 2, 3]], "max_new_tokens": 5},
            ),
        )
        bad = await loop.run_in_executor(
            None,
            lambda: fetch(
                "/v1/generate",
                {"tokens": [[999]], "max_new_tokens": 5},
            ),
        )
        score = await loop.run_in_executor(
            None,
            lambda: fetch("/v1/score", {"tokens": [[1, 2, 3, 4]]}),
        )
        bad_score = await loop.run_in_executor(
            None,
            lambda: fetch("/v1/score", {"tokens": [[7]]}),
        )
        await server.stop()
        return health, gen, bad, score, bad_score

    import json
    import urllib.error

    health, gen, bad, score, bad_score = run(scenario(), timeout=120)
    assert health[0] == 200
    assert gen[0] == 200
    out = json.loads(gen[1])["tokens"]
    assert len(out) == 1 and len(out[0]) == 5
    assert bad[0] == 422 and "token ids" in bad[1]

    # teacher-forced scoring: one logprob per continuation token, all
    # negative, matching the forward's log-softmax
    assert score[0] == 200
    scored = json.loads(score[1])
    assert len(scored["logprobs"][0]) == 3
    assert all(lp < 0 for lp in scored["logprobs"][0])
    from containerpilot_tpu.models.transformer import forward as _fwd

    toks = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    logp = jax.nn.log_softmax(_fwd(params, toks[:, :-1], cfg), axis=-1)
    expect = [float(logp[0, i, int(toks[0, i + 1])]) for i in range(3)]
    np.testing.assert_allclose(
        scored["logprobs"][0], expect, rtol=1e-3, atol=1e-3
    )
    np.testing.assert_allclose(
        scored["sums"][0], sum(expect), rtol=1e-3, atol=1e-3
    )
    assert bad_score[0] == 422 and ">= 2 ids" in bad_score[1]


def test_generate_per_row_params_and_key_independence():
    """Per-row sampling knobs and keys: a greedy row batched next to a
    sampled row matches its solo greedy output, and a sampled row's
    output is independent of what it's batched with."""
    from containerpilot_tpu.models.decode import generate

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    rows = jax.random.randint(
        jax.random.PRNGKey(1), (2, 4), 0, 64, jnp.int32
    )
    solo_greedy = generate(params, rows[:1], cfg, 8, 16)
    key_b = jax.random.PRNGKey(7)
    solo_sampled = generate(
        params, rows[1:], cfg, 8, 16, temperature=1.0, top_k=8,
        rng=key_b[None, :],
    )
    mixed = generate(
        params, rows, cfg, 8, 16,
        temperature=[0.0, 1.0], top_k=[0, 8],
        rng=jnp.stack([jax.random.PRNGKey(0), key_b]),
    )
    np.testing.assert_array_equal(
        np.asarray(solo_greedy[0]), np.asarray(mixed[0])
    )
    np.testing.assert_array_equal(
        np.asarray(solo_sampled[0]), np.asarray(mixed[1])
    )
    with pytest.raises(ValueError, match="scalar or \\[batch\\]"):
        generate(params, rows, cfg, 8, 16, temperature=[0.5, 0.5, 0.5])


def test_inference_server_batches_concurrent_requests(run):
    """Concurrent clients coalesce into fewer device calls with
    unchanged per-request results."""
    import urllib.request

    from containerpilot_tpu.models.transformer import init_params
    from containerpilot_tpu.workload.serve import InferenceServer

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    server = InferenceServer(cfg, params, "127.0.0.1", 0, max_len=32)

    def fetch(body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/generate",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            return json.loads(resp.read())

    bodies = [
        {"tokens": [[1, 2, 3]], "max_new_tokens": 6,
         "temperature": 1.0, "top_k": 8, "seed": i}
        for i in range(6)
    ] + [{"tokens": [[1, 2, 3]], "max_new_tokens": 6}]  # one greedy

    async def scenario():
        import asyncio

        await server.run()
        loop = asyncio.get_event_loop()
        # sequential baseline (one request at a time)
        sequential = []
        for body in bodies:
            sequential.append(
                await loop.run_in_executor(None, fetch, body)
            )
        calls_before = server.batch_stats["calls"]
        concurrent = await asyncio.gather(*[
            loop.run_in_executor(None, fetch, body) for body in bodies
        ])
        coalesced_calls = server.batch_stats["calls"] - calls_before
        await server.stop()
        return sequential, concurrent, coalesced_calls

    import json

    sequential, concurrent, coalesced_calls = run(scenario(), timeout=300)
    # identical results regardless of batching (per-row keys from each
    # request's seed)
    assert sequential == list(concurrent)
    # and the 7 concurrent requests used fewer device calls
    assert coalesced_calls < len(bodies), (
        f"no coalescing: {coalesced_calls} calls for {len(bodies)} requests"
    )


def test_inference_server_speculative(run):
    """Two servers, same weights, one speculative: identical greedy
    output over HTTP; sampled and batched requests fall back."""
    import urllib.request

    from containerpilot_tpu.models.transformer import init_params
    from containerpilot_tpu.workload.serve import InferenceServer

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=3, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    vanilla = InferenceServer(cfg, params, "127.0.0.1", 0, max_len=64)
    spec = InferenceServer(
        cfg, params, "127.0.0.1", 0, max_len=64,
        draft_layers=1, speculate=4,
    )
    with pytest.raises(ValueError, match="speculate"):
        InferenceServer(cfg, params, "127.0.0.1", 0, max_len=64,
                        draft_layers=1, speculate=0)

    def fetch(port, body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/generate",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            return json.loads(resp.read())

    async def scenario():
        import asyncio

        await vanilla.run()
        await spec.run()
        loop = asyncio.get_event_loop()
        greedy_body = {"tokens": [[3, 1, 4, 1, 5]], "max_new_tokens": 24}
        a = await loop.run_in_executor(
            None, lambda: fetch(vanilla.port, greedy_body)
        )
        b = await loop.run_in_executor(
            None, lambda: fetch(spec.port, greedy_body)
        )
        # eos trim must agree between the padded and speculative paths
        eos = a["tokens"][0][2]
        eos_body = {**greedy_body, "eos_id": eos}
        ae = await loop.run_in_executor(
            None, lambda: fetch(vanilla.port, eos_body)
        )
        be = await loop.run_in_executor(
            None, lambda: fetch(spec.port, eos_body)
        )
        sampled = await loop.run_in_executor(
            None, lambda: fetch(spec.port, {
                "tokens": [[3, 1, 4]], "max_new_tokens": 8,
                "temperature": 1.0, "seed": 7,
            })
        )
        batched = await loop.run_in_executor(
            None, lambda: fetch(spec.port, {
                "tokens": [[1, 2], [3, 4]], "max_new_tokens": 4,
            })
        )

        def model_info():
            with urllib.request.urlopen(
                f"http://127.0.0.1:{spec.port}/v1/model", timeout=5
            ) as resp:
                return json.loads(resp.read())

        info = await loop.run_in_executor(None, model_info)
        await vanilla.stop()
        await spec.stop()
        return a, b, ae, be, sampled, batched, info

    import json

    a, b, ae, be, sampled, batched, info = run(scenario(), timeout=300)
    assert a == b
    assert ae == be
    assert len(sampled["tokens"][0]) == 8
    assert len(batched["tokens"]) == 2 and len(batched["tokens"][0]) == 4
    # observability: /v1/model reports the speculative + batching
    # setup, including the step-program engine the greedy requests
    # rode (draft+verify = 2 device dispatches per round)
    spec_info = dict(info["speculative"])
    engine_stats = spec_info.pop("engine")
    assert spec_info == {"draft_layers": 1, "speculate": 4}
    assert engine_stats["slots"] == 1
    assert engine_stats["dispatches"] >= 2
    assert info["batching"]["device_calls"] >= 2  # sampled + batched


def test_lora_zero_init_and_training(tmp_path):
    """A fresh adapter reproduces the base exactly (B = 0); training
    it lowers the loss with the base frozen; the adapter checkpoints
    round-trip, including the params-only restore serving uses."""
    from containerpilot_tpu.models.lora import apply_lora, init_lora_params
    from containerpilot_tpu.parallel import (
        make_lora_train_step,
        restore_checkpoint,
        restore_params,
        save_checkpoint,
    )
    from containerpilot_tpu.parallel.sharding import shard_params

    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq_len=64, dtype=jnp.float32,
    )
    mesh = make_mesh(jax.devices()[:8])
    base = shard_params(
        init_params(jax.random.PRNGKey(0), cfg), mesh, cfg
    )
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (8, 33), 0, cfg.vocab_size, jnp.int32
    )

    # exact zero-delta at init
    lora = init_lora_params(jax.random.PRNGKey(2), cfg, rank=4)
    merged = apply_lora(base, lora, cfg)
    np.testing.assert_array_equal(
        np.asarray(forward(base, tokens[:, :-1], cfg)),
        np.asarray(forward(merged, tokens[:, :-1], cfg)),
    )

    init_fn, step_fn, abstract = make_lora_train_step(
        cfg, mesh, rank=4, learning_rate=1e-2
    )
    state = init_fn(jax.random.PRNGKey(3))
    base_before = jax.tree_util.tree_map(np.asarray, base)
    losses = []
    for _ in range(15):
        state, loss = step_fn(state, base, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.1, losses
    # the base never moved; the adapter did
    for a, b in zip(
        jax.tree_util.tree_leaves(base_before),
        jax.tree_util.tree_leaves(base),
    ):
        np.testing.assert_array_equal(a, np.asarray(b))
    assert float(jnp.abs(state.params["wq_b"]).max()) > 0

    # resume + serving restore
    save_checkpoint(str(tmp_path), 15, state)
    resumed = restore_checkpoint(str(tmp_path), abstract)
    assert int(resumed.step) == 15
    lora_only, step_n = restore_params(str(tmp_path), abstract)
    assert int(step_n) == 15
    np.testing.assert_array_equal(
        np.asarray(lora_only["wq_a"]), np.asarray(state.params["wq_a"])
    )

    with pytest.raises(ValueError, match="rank"):
        init_lora_params(jax.random.PRNGKey(0), cfg, rank=0)


def test_decode_bench_plumbing():
    """bench.py's decode benchmark must run end-to-end on the CPU
    backend with an override config (the real run needs the chip, but
    a broken bench should fail CI, not the round's bench artifact)."""
    import bench  # conftest puts the repo root on sys.path

    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=2, n_layers=2, d_ff=128,
        max_seq_len=512, dtype=jnp.float32,
    )
    out = bench.decode_bench(cfg, max_new=8, prompt_len=16)
    assert out["b1_tok_s"] > 0 and out["b8_tok_s"] > 0
    assert out["batch_throughput_x"] > 0
    assert "override" in out["model"]
    adm = bench.slot_admission_bench(cfg, max_new=8, prompt_len=16)
    assert adm["short_latency_ms_sequential"] > 0
    assert adm["short_latency_ms_slots"] > 0
    assert adm["admission_speedup_x"] > 0


def test_moe_forward_and_training():
    """Switch-MoE model: finite forward, aux loss present, loss drops
    under training, expert weights actually expert-parallel."""
    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq_len=64, moe_experts=4,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    assert "moe_w_in" in params["layers"] and "w_gate" not in params["layers"]
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size, jnp.int32
    )
    from containerpilot_tpu.models.transformer import forward_with_aux

    logits, aux = forward_with_aux(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert bool(jnp.isfinite(logits).all())
    assert float(aux) > 0.0  # load-balance loss is live

    mesh = make_mesh(jax.devices()[:8])
    state = init_train_state(jax.random.PRNGKey(0), cfg, mesh,
                             learning_rate=1e-2)
    step = make_train_step(cfg, mesh, learning_rate=1e-2)
    batch = jax.random.randint(
        jax.random.PRNGKey(2), (4, 33), 0, cfg.vocab_size, jnp.int32
    )
    first = None
    for _ in range(6):
        state, loss = step(state, batch)
        if first is None:
            first = float(loss)
    assert float(loss) < first, (first, float(loss))
    # expert axis sharded over the 4-way model axis (expert parallelism)
    spec = state.params["layers"]["moe_w_in"].sharding.spec
    assert spec[1] == "model", spec


def test_moe_decode_parity():
    """Incremental decode equals full forward for the MoE model too."""
    from containerpilot_tpu.models.decode import decode_step, prefill

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=32, moe_experts=2, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    # drop-free routing means parity must hold for EVERY prompt, not
    # just a lucky seed — sweep several
    for seed in (1, 7, 23):
        tokens = jax.random.randint(
            jax.random.PRNGKey(seed), (1, 8), 0, cfg.vocab_size, jnp.int32
        )
        full = forward(params, tokens, cfg)
        logits, cache = prefill(params, tokens[:, :4], cfg, max_len=16)
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(full[:, 3]), rtol=2e-4, atol=2e-4,
            err_msg=f"seed {seed} prefill",
        )
        for i in range(4, 8):
            logits, cache = decode_step(params, cache, tokens[:, i], cfg)
            np.testing.assert_allclose(
                np.asarray(logits), np.asarray(full[:, i]), rtol=2e-4,
                atol=2e-4, err_msg=f"seed {seed} position {i}",
            )


def test_distributed_initialize_from_catalog_single_process(tmp_path):
    """The catalog rendezvous path: process 0 registers the coordinator
    and initializes; (multi-process needs multiple hosts, so we drive
    the registration + discovery logic plus a real 1-process init)."""
    from containerpilot_tpu.discovery import FileCatalogBackend
    from containerpilot_tpu.parallel.distributed import (
        COORDINATOR_SERVICE,
        _discover_coordinator,
    )

    backend = FileCatalogBackend(str(tmp_path))
    # a "process 0" on another host registered already:
    from containerpilot_tpu.discovery import ServiceRegistration

    backend.service_register(
        ServiceRegistration(
            id="jax-coordinator-host0", name=COORDINATOR_SERVICE,
            port=8476, address="10.0.0.1", ttl=600,
        ),
        status="passing",
    )
    addr = _discover_coordinator(backend, 8476, timeout=5, poll_interval=0.1)
    assert addr == "10.0.0.1:8476"
    with pytest.raises(TimeoutError):
        _discover_coordinator(
            FileCatalogBackend(str(tmp_path / "empty")), 8476,
            timeout=0.3, poll_interval=0.1,
        )


_RENDEZVOUS_WORKER = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
pid, n, catalog, coord_port = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], int(sys.argv[4])
)
from containerpilot_tpu.discovery.consul import ConsulBackend
from containerpilot_tpu.parallel.distributed import initialize_from_catalog

backend = ConsulBackend(address=catalog)
initialize_from_catalog(
    backend, pid, n, coordinator_port=coord_port,
    advertise_address="127.0.0.1", timeout=90, poll_interval=0.2,
)
assert jax.process_count() == n, jax.process_count()
import jax.numpy as jnp

total = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
    jnp.ones((jax.local_device_count(),), jnp.float32)
)
print("PSUM", float(total[0]), flush=True)
"""


def test_distributed_two_process_catalog_rendezvous(tmp_path):
    """TWO real OS processes rendezvous through a live catalog server
    and complete a cross-process psum (reference scenario:
    integration_tests/tests/test_discovery_consul — two containers
    finding each other through the catalog)."""
    import socket as socketlib
    import subprocess
    import sys
    import time as timelib
    import urllib.request

    import os

    def free_port():
        with socketlib.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    catalog_port, coord_port = free_port(), free_port()
    worker = tmp_path / "worker.py"
    worker.write_text(_RENDEZVOUS_WORKER)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo)
    env.pop("XLA_FLAGS", None)  # 1 CPU device per process

    server = subprocess.Popen(
        [sys.executable, "-m", "containerpilot_tpu",
         "-catalog-server", f"127.0.0.1:{catalog_port}"],
        cwd=repo, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        deadline = timelib.monotonic() + 30
        while True:
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{catalog_port}"
                    "/v1/health/service/none",
                    timeout=1,
                )
                break
            except Exception:
                if timelib.monotonic() > deadline:
                    raise TimeoutError("catalog server never came up")
                timelib.sleep(0.2)

        procs = [
            subprocess.Popen(
                [sys.executable, str(worker), str(pid), "2",
                 f"127.0.0.1:{catalog_port}", str(coord_port)],
                cwd=repo, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )
            for pid in (0, 1)
        ]
        outs = [p.communicate(timeout=180) for p in procs]
        for p, (out, err) in zip(procs, outs):
            assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
            assert "PSUM 2.0" in out, (out, err[-500:])
    finally:
        server.terminate()
        server.wait(timeout=10)


def test_pipeline_parallel_forward_parity():
    """GPipe-style pipeline over 4 stages must reproduce the plain
    forward exactly (same params, dense model)."""
    import numpy as _np
    from jax.sharding import Mesh

    from containerpilot_tpu.parallel.pipeline import (
        pipeline_forward_with_aux,
        pipeline_loss_fn,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=4, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    mesh = Mesh(_np.asarray(jax.devices()[:4]), ("pipe",))
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (8, 12), 0, cfg.vocab_size, jnp.int32
    )
    ref = forward(params, tokens, cfg)
    out, aux = pipeline_forward_with_aux(
        params, tokens, cfg, mesh, n_microbatches=4
    )
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(out), rtol=2e-4, atol=2e-4
    )
    assert float(aux) == 0.0  # dense model: no MoE aux

    # training path: grads flow through ppermute/fori_loop
    grads = jax.grad(
        lambda p: pipeline_loss_fn(p, tokens, cfg, mesh, n_microbatches=4)
    )(params)
    flat, _ = jax.tree_util.tree_flatten(grads)
    assert all(bool(jnp.isfinite(g).all()) for g in flat)
    # layer grads are nonzero (the pipeline actually trained all stages)
    assert float(jnp.abs(grads["layers"]["wq"]).sum()) > 0


def test_pipeline_validates_inputs():
    import numpy as _np
    from jax.sharding import Mesh

    from containerpilot_tpu.parallel.pipeline import (
        pipeline_forward_with_aux,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=3, d_ff=64,
        max_seq_len=32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    mesh = Mesh(_np.asarray(jax.devices()[:4]), ("pipe",))
    tokens = jnp.zeros((8, 8), jnp.int32)
    with pytest.raises(ValueError, match="not divisible by 4 stages"):
        pipeline_forward_with_aux(params, tokens, cfg, mesh)
    cfg2 = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=4, d_ff=64,
        max_seq_len=32,
    )
    params2 = init_params(jax.random.PRNGKey(0), cfg2)
    with pytest.raises(ValueError, match="microbatches"):
        pipeline_forward_with_aux(
            params2, jnp.zeros((6, 8), jnp.int32), cfg2, mesh,
            n_microbatches=4,
        )


def test_pipeline_composes_with_data_parallelism():
    """dp x pp: a ("data", "pipe") mesh shards microbatch contents over
    data while stages stream over pipe; parity with the plain forward."""
    import numpy as _np
    from jax.sharding import Mesh

    from containerpilot_tpu.parallel.pipeline import (
        pipeline_forward_with_aux,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=4, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    mesh = Mesh(
        _np.asarray(jax.devices()[:8]).reshape(2, 4), ("data", "pipe")
    )
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (8, 12), 0, cfg.vocab_size, jnp.int32
    )
    ref = forward(params, tokens, cfg)
    out, _aux = pipeline_forward_with_aux(
        params, tokens, cfg, mesh, n_microbatches=4
    )
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(out), rtol=2e-4, atol=2e-4
    )
    # grads flow through the data-sharded specs and the aux pmean
    from containerpilot_tpu.parallel.pipeline import pipeline_loss_fn

    grads = jax.grad(
        lambda p: pipeline_loss_fn(p, tokens, cfg, mesh, n_microbatches=4)
    )(params)
    flat, _ = jax.tree_util.tree_flatten(grads)
    assert all(bool(jnp.isfinite(g).all()) for g in flat)
    # microbatch size must divide the data axis
    with pytest.raises(ValueError, match="data axis"):
        pipeline_forward_with_aux(
            params, tokens[:4], cfg, mesh, n_microbatches=4
        )


def test_pipeline_composes_with_tensor_parallelism():
    """dp x pp x tp: layers shard over pipe stages while the model axis
    stays live (auto-partitioned) inside each stage; forward parity with
    the unpipelined model and a full pipelined train step."""
    from containerpilot_tpu.parallel import (
        init_train_state as _init,
        make_pipeline_train_step,
    )
    from containerpilot_tpu.parallel.pipeline import (
        pipeline_forward_with_aux,
        pipeline_sharding_rules,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=4, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh(jax.devices()[:8], plan=MeshPlan(2, 2, pipe=2))
    assert mesh.axis_names == ("data", "pipe", "model")

    # in-stage tp specs survive the pipe composition
    rules = pipeline_sharding_rules(cfg, mesh)
    assert tuple(rules["layers"]["wq"]) == ("pipe", None, "model", None)

    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (8, 12), 0, cfg.vocab_size, jnp.int32
    )
    ref = forward(params, tokens, cfg)
    # auto-axis shard_map must run under jit (the eager impl path does
    # not support auto axes) — which is the only real usage anyway
    out, _aux = jax.jit(
        lambda p, t: pipeline_forward_with_aux(p, t, cfg, mesh, 4)
    )(params, tokens)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(out), rtol=2e-4, atol=2e-4
    )

    state = _init(jax.random.PRNGKey(0), cfg, mesh, rules=rules)
    step = make_pipeline_train_step(cfg, mesh, n_microbatches=4)
    batch = jax.random.randint(
        jax.random.PRNGKey(2), (8, 13), 0, cfg.vocab_size, jnp.int32
    )
    state, loss = step(state, batch)
    assert bool(jnp.isfinite(loss))
    assert int(state.step) == 1


def test_pipeline_composes_with_expert_parallelism():
    """pp x ep x dp: switch-MoE experts shard over the auto model axis
    inside each pipeline stage."""
    from containerpilot_tpu.parallel import (
        init_train_state as _init,
        make_pipeline_train_step,
    )
    from containerpilot_tpu.parallel.pipeline import pipeline_sharding_rules

    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=2, n_layers=4, d_ff=128,
        max_seq_len=32, moe_experts=2, dtype=jnp.float32,
    )
    mesh = make_mesh(jax.devices()[:8], plan=MeshPlan(2, 2, pipe=2))
    rules = pipeline_sharding_rules(cfg, mesh)
    assert tuple(rules["layers"]["moe_w_in"]) == ("pipe", "model", None, None)
    state = _init(jax.random.PRNGKey(0), cfg, mesh, rules=rules)
    step = make_pipeline_train_step(cfg, mesh, n_microbatches=4)
    tokens = jax.random.randint(
        jax.random.PRNGKey(2), (8, 33), 0, cfg.vocab_size, jnp.int32
    )
    state, loss = step(state, tokens)
    assert bool(jnp.isfinite(loss))


def test_memory_efficient_attention_value_and_grad():
    """Flash-algorithm training attention: forward and ALL THREE input
    gradients must match the einsum reference."""
    from containerpilot_tpu.ops.flash_training import (
        memory_efficient_attention,
    )

    rng = jax.random.PRNGKey(0)
    kq, kk, kv, kd = jax.random.split(rng, 4)
    shape = (2, 256, 2, 32)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    cotangent = jax.random.normal(kd, shape, jnp.float32)

    ref_out = causal_attention(q, k, v)
    out = memory_efficient_attention(q, k, v, 64)
    np.testing.assert_allclose(
        np.asarray(ref_out), np.asarray(out), rtol=2e-4, atol=2e-4
    )

    def ref_loss(q, k, v):
        return jnp.sum(causal_attention(q, k, v) * cotangent)

    def mea_loss(q, k, v):
        return jnp.sum(memory_efficient_attention(q, k, v, 64) * cotangent)

    ref_grads = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    mea_grads = jax.grad(mea_loss, argnums=(0, 1, 2))(q, k, v)
    for name, rg, mg in zip("qkv", ref_grads, mea_grads):
        np.testing.assert_allclose(
            np.asarray(rg), np.asarray(mg), rtol=5e-4, atol=5e-4,
            err_msg=f"d{name}",
        )


def test_memory_efficient_attention_in_model_training():
    """The model trains with memory-efficient attention bound in."""
    import dataclasses

    from containerpilot_tpu.ops.flash_training import (
        memory_efficient_attention,
    )

    cfg = dataclasses.replace(
        TransformerConfig(
            vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
            max_seq_len=64, dtype=jnp.float32,
        ),
        attention_fn=lambda q, k, v: memory_efficient_attention(q, k, v, 32),
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 65), 0, 64, jnp.int32
    )
    loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg)
    assert bool(jnp.isfinite(loss))
    flat, _ = jax.tree_util.tree_flatten(grads)
    assert all(bool(jnp.isfinite(g).all()) for g in flat)


def test_abstract_restore_skips_materialization(tmp_path):
    """Resume via the abstract (eval_shape) target: identical result to
    restoring into a materialized state, with correct shardings."""
    from containerpilot_tpu.parallel import (
        restore_checkpoint,
        save_checkpoint,
    )
    from containerpilot_tpu.parallel.train import abstract_train_state

    mesh = make_mesh(jax.devices()[:8])
    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq_len=64,
    )
    rng = jax.random.PRNGKey(0)
    state = init_train_state(rng, cfg, mesh)
    step = make_train_step(cfg, mesh)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (4, 33), 0, cfg.vocab_size, jnp.int32
    )
    state, _ = step(state, tokens)
    ckdir = str(tmp_path / "ck")
    save_checkpoint(ckdir, 1, state)

    abstract = abstract_train_state(rng, cfg, mesh)
    restored = restore_checkpoint(ckdir, abstract)
    assert restored is not None
    assert int(restored.step) == 1
    # shardings landed where the train step expects: step still runs
    wq = restored.params["layers"]["wq"]
    assert wq.sharding.spec == state.params["layers"]["wq"].sharding.spec
    restored, loss = step(restored, tokens)
    assert bool(jnp.isfinite(loss))


def test_gqa_forward_and_decode_parity():
    """Grouped-query attention: 4 query heads over 2 kv heads — the KV
    cache shrinks and incremental decode still matches the forward."""
    from containerpilot_tpu.models.decode import decode_step, init_cache, prefill

    cfg = TransformerConfig(
        vocab_size=64, d_model=64, n_heads=4, n_kv_heads=2, n_layers=2,
        d_ff=64, max_seq_len=32, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    assert params["layers"]["wk"].shape == (2, 64, 2, 16)  # kv heads
    assert params["layers"]["wq"].shape == (2, 64, 4, 16)  # full heads
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (1, 10), 0, cfg.vocab_size, jnp.int32
    )
    full = forward(params, tokens, cfg)
    assert bool(jnp.isfinite(full).all())

    cache = init_cache(cfg, 1, 16)
    assert cache["k"].shape == (2, 1, 16, 2, 16)  # halved kv-head cache

    logits, cache = prefill(params, tokens[:, :5], cfg, max_len=16)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full[:, 4]), rtol=2e-4, atol=2e-4
    )
    for i in range(5, 10):
        logits, cache = decode_step(params, cache, tokens[:, i], cfg)
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(full[:, i]), rtol=2e-4,
            atol=2e-4, err_msg=f"position {i}",
        )


def test_gqa_trains_sharded():
    """GQA + tp: kv heads (2) shard over a 2-way model axis."""
    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2, n_layers=2,
        d_ff=128, max_seq_len=64,
    )
    mesh = make_mesh(jax.devices()[:8], plan=MeshPlan(data=4, model=2))
    state = init_train_state(jax.random.PRNGKey(0), cfg, mesh)
    step = make_train_step(cfg, mesh)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (4, 33), 0, cfg.vocab_size, jnp.int32
    )
    state, loss = step(state, tokens)
    assert bool(jnp.isfinite(loss))


def test_gqa_default_mesh_replicates_small_kv_axis():
    """GQA with kv_heads smaller than the auto-picked model axis must
    place (replicate wk/wv) instead of crashing."""
    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2, n_layers=2,
        d_ff=128, max_seq_len=64,
    )
    mesh = make_mesh(jax.devices()[:8])  # auto plan: model=4 > kv=2
    state = init_train_state(jax.random.PRNGKey(0), cfg, mesh)
    step = make_train_step(cfg, mesh)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (4, 33), 0, cfg.vocab_size, jnp.int32
    )
    state, loss = step(state, tokens)
    assert bool(jnp.isfinite(loss))
    from jax.sharding import PartitionSpec as P

    assert state.params["layers"]["wk"].sharding.spec == P(
        None, None, None, None
    )


def test_int8_quantized_matmul():
    """Weight-only int8: quantization error bounded, pallas kernel
    (interpret mode) matches the XLA dequant path."""
    from containerpilot_tpu.ops import (
        int8_matmul,
        int8_matmul_pallas,
        quantize_int8,
    )

    kx, kw = jax.random.split(jax.random.PRNGKey(0))
    x = jax.random.normal(kx, (128, 256), jnp.float32)
    w = jax.random.normal(kw, (256, 384), jnp.float32)
    w_q, scales = quantize_int8(w)
    assert w_q.dtype == jnp.int8 and scales.shape == (384,)
    # dequantized weights approximate the originals per-channel
    w_hat = w_q.astype(jnp.float32) * scales[None, :]
    assert float(jnp.max(jnp.abs(w_hat - w))) < float(jnp.max(scales)) * 0.51

    exact = x @ w
    ref = int8_matmul(x, w_q, scales)
    # int8 matmul error grows with sqrt(K); relative tolerance
    rel = float(jnp.max(jnp.abs(ref - exact)) / jnp.max(jnp.abs(exact)))
    assert rel < 0.02, rel
    out = int8_matmul_pallas(x, w_q, scales)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(out), rtol=2e-3, atol=2e-3
    )
    with pytest.raises(ValueError, match="not divisible"):
        int8_matmul_pallas(x[:100], w_q, scales)
    with pytest.raises(ValueError, match="inner dims"):
        int8_matmul_pallas(x[:, :128], w_q, scales)


def test_int8_model_quantization_end_to_end():
    """Model-level weight-only int8: ~4x smaller params, small logit
    error, and the quantized decode path matches the quantized forward
    (teacher forcing) so serving is self-consistent."""
    from containerpilot_tpu.models.decode import decode_step, generate, prefill
    from containerpilot_tpu.models.quantized import (
        is_quantized,
        param_bytes,
        quantize_model_params,
    )

    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2, n_layers=2,
        d_ff=128, max_seq_len=32, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    pq = quantize_model_params(params)
    assert is_quantized(pq) and not is_quantized(params)
    assert param_bytes(params) / param_bytes(pq) > 3.0

    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 10), 0, cfg.vocab_size, jnp.int32
    )
    full = forward(params, tokens, cfg)
    quant = forward(pq, tokens, cfg)
    rel = float(jnp.max(jnp.abs(full - quant)) / jnp.max(jnp.abs(full)))
    assert rel < 0.05, rel

    # quantized incremental decode == quantized forward, per position
    logits, cache = prefill(pq, tokens[:, :5], cfg, max_len=16)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(quant[:, 4]), rtol=2e-4, atol=2e-4
    )
    for i in range(5, 10):
        logits, cache = decode_step(pq, cache, tokens[:, i], cfg)
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(quant[:, i]), rtol=2e-4,
            atol=2e-4, err_msg=f"position {i}",
        )
    out = generate(pq, tokens[:, :4], cfg, max_new_tokens=4, max_len=16)
    assert out.shape == (2, 4)


def test_int8_fused_decode_matches_dense_dequant():
    """On a tile-aligned model the decode step routes its projections
    through the fused int8 pallas GEMM; logits must match the
    dense-dequant path (same math, different streaming)."""
    from containerpilot_tpu.models import decode as decode_mod
    from containerpilot_tpu.models.decode import decode_step, prefill
    from containerpilot_tpu.models.quantized import (
        can_fuse_int8,
        quantize_model_params,
    )

    cfg = TransformerConfig(
        vocab_size=256, d_model=128, n_heads=1, n_layers=2, d_ff=128,
        max_seq_len=32, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    pq = quantize_model_params(params)
    assert can_fuse_int8(pq["layers"], cfg, rows=2)
    # tiny dims or MoE fall back to dense dequant
    assert not can_fuse_int8(pq["layers"], cfg, rows=10_000)
    small = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq_len=32, dtype=jnp.float32,
    )
    small_q = quantize_model_params(init_params(jax.random.PRNGKey(0), small))
    assert not can_fuse_int8(small_q["layers"], small, rows=2)

    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab_size, jnp.int32
    )
    with jax.default_matmul_precision("float32"):
        quant_fwd = forward(pq, tokens, cfg)
        logits, cache = prefill(pq, tokens[:, :4], cfg, max_len=16)
        for i in range(4, 8):
            logits, cache = decode_step(pq, cache, tokens[:, i], cfg)
            np.testing.assert_allclose(
                np.asarray(logits), np.asarray(quant_fwd[:, i]),
                rtol=2e-3, atol=2e-3, err_msg=f"position {i}",
            )


def test_int8_moe_quantization():
    """MoE expert weights quantize too."""
    from containerpilot_tpu.models.quantized import quantize_model_params

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=32, moe_experts=2, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    pq = quantize_model_params(params)
    assert "moe_w_in_q" in pq["layers"]
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (1, 8), 0, 64, jnp.int32
    )
    full = forward(params, tokens, cfg)
    quant = forward(pq, tokens, cfg)
    rel = float(jnp.max(jnp.abs(full - quant)) / jnp.max(jnp.abs(full)))
    assert rel < 0.08, rel


def test_moe_capacity_training_mode():
    """Capacity-bounded MoE: trains (loss drops), matches drop-free
    routing when capacity is ample, diverges under pressure, and is
    refused by the decode path."""
    import dataclasses

    from containerpilot_tpu.models.decode import prefill

    base = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq_len=64, moe_experts=2, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), base)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 16), 0, base.vocab_size, jnp.int32
    )
    free = forward(params, tokens, base)
    ample = dataclasses.replace(base, moe_train_capacity=8.0)
    np.testing.assert_allclose(
        np.asarray(free), np.asarray(forward(params, tokens, ample)),
        rtol=1e-4, atol=1e-4,
    )  # capacity >= every queue: identical routing
    tight = dataclasses.replace(base, moe_train_capacity=0.5)
    squeezed = forward(params, tokens, tight)
    assert float(jnp.max(jnp.abs(free - squeezed))) > 1e-3  # drops happened

    # trains end-to-end
    mesh = make_mesh(jax.devices()[:8], plan=MeshPlan(data=4, model=2))
    state = init_train_state(jax.random.PRNGKey(0), tight, mesh,
                             learning_rate=1e-2)
    step = make_train_step(tight, mesh, learning_rate=1e-2)
    batch = jax.random.randint(
        jax.random.PRNGKey(2), (4, 33), 0, base.vocab_size, jnp.int32
    )
    first = None
    for _ in range(5):
        state, loss = step(state, batch)
        if first is None:
            first = float(loss)
    assert float(loss) < first

    with pytest.raises(ValueError, match="moe_train_capacity"):
        prefill(params, tokens[:, :8], tight, max_len=32)


def test_moe_capacity_requires_experts():
    with pytest.raises(ValueError, match="requires moe_experts"):
        TransformerConfig(moe_train_capacity=1.0)


def test_moe_sparse_dispatch_flops_scale_with_capacity():
    """The capacity layer's compiled FLOPs must scale with the capacity
    bound, not with E x s — evidence that dispatch is sparse
    gather/scatter, not the dense one-hot einsums."""
    from containerpilot_tpu.models.moe import moe_layer, moe_layer_capacity

    b, s, d, f, E = 2, 256, 64, 128, 8
    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(rng, (b, s, d), jnp.float32)
    router = jax.random.normal(jax.random.PRNGKey(1), (d, E), jnp.float32)
    w_in = jax.random.normal(jax.random.PRNGKey(2), (E, d, f), jnp.float32)
    w_out = jax.random.normal(jax.random.PRNGKey(3), (E, f, d), jnp.float32)

    def flops(fn, *args):
        compiled = jax.jit(fn).lower(*args).compile()
        (analysis,) = [compiled.cost_analysis()] if isinstance(
            compiled.cost_analysis(), dict
        ) else [compiled.cost_analysis()[0]]
        return analysis["flops"]

    dense = flops(
        lambda x: moe_layer(x, router, w_in, w_out)[0], x
    )
    tight = flops(
        lambda x: moe_layer_capacity(x, router, w_in, w_out, 1.0)[0], x
    )
    double = flops(
        lambda x: moe_layer_capacity(x, router, w_in, w_out, 2.0)[0], x
    )
    # drop-free dense dispatch does E x s expert work; capacity 1.0
    # does ~s total expert work — at E=8 that's a large gap
    assert tight < dense / 3, (tight, dense)
    # expert compute tracks the capacity bound
    assert tight < double, (tight, double)


def test_fsdp_shards_params_and_matches_plain_step():
    """FSDP (ZeRO-3): params AND adam moments shard over the data axis
    (per-device model state drops by the dp factor) while the update
    stays numerically equivalent to the replicated-params step."""
    from containerpilot_tpu.parallel import fsdp_sharding_rules
    from containerpilot_tpu.parallel.train import train_state_shardings

    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq_len=64, dtype=jnp.float32,
    )
    mesh = make_mesh(jax.devices()[:8])  # data=2, model=4
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (4, 33), 0, cfg.vocab_size, jnp.int32
    )

    rules = fsdp_sharding_rules(cfg, mesh)
    # every large param gains a data axis; the scan/layer axis never
    # takes it (slicing a scan operand across devices would force a
    # per-iteration gather)
    assert "data" in rules["embed"]
    for name, spec in rules["layers"].items():
        assert spec[0] is None, (name, spec)
        assert "data" in spec, (name, spec)

    plain = init_train_state(jax.random.PRNGKey(0), cfg, mesh)
    fs = init_train_state(jax.random.PRNGKey(0), cfg, mesh, rules=rules)

    # params and moments really are sharded over data: each device
    # holds 1/8 of wq (2-way data x 4-way model) vs 1/4 replicated
    shard_elems = lambda a: a.addressable_shards[0].data.size
    wq_p, wq_f = plain.params["layers"]["wq"], fs.params["layers"]["wq"]
    assert shard_elems(wq_f) * 2 == shard_elems(wq_p)
    mu_f = fs.opt_state[1][0].mu["layers"]["wq"]
    assert "data" in mu_f.sharding.spec
    assert shard_elems(mu_f) == shard_elems(wq_f)

    # the canonical shardings agree with what init produced, and
    # zero1=True composes (the moments keep the fsdp placement rather
    # than double-consuming the data axis)
    shardings = train_state_shardings(cfg, mesh, rules=rules, zero1=True)
    assert shardings.opt_state[1][0].mu["layers"]["wq"] == mu_f.sharding

    step_plain = make_train_step(cfg, mesh)
    step_fsdp = make_train_step(cfg, mesh, fsdp=True)
    plain, loss_a = step_plain(plain, tokens)
    fs, loss_b = step_fsdp(fs, tokens)
    np.testing.assert_allclose(
        float(loss_a), float(loss_b), rtol=1e-6, atol=1e-7
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(plain.params),
        jax.tree_util.tree_leaves(fs.params),
    ):
        # reduce-scattered grads reassociate float sums across devices
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
        )


def test_ema_tracks_params_and_checkpoints(tmp_path):
    """with_ema keeps a decay-weighted shadow of the params inside the
    optimizer state: exact vs a hand-rolled recurrence, resolvable by
    the sharding rules, and carried through a checkpoint roundtrip."""
    from containerpilot_tpu.parallel import (
        ema_params,
        make_optimizer,
        restore_checkpoint,
        save_checkpoint,
        with_ema,
    )
    from containerpilot_tpu.parallel import abstract_train_state

    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq_len=64, dtype=jnp.float32,
    )
    mesh = make_mesh(jax.devices()[:8])
    decay = 0.9
    opt = with_ema(make_optimizer(1e-2), decay)
    state = init_train_state(jax.random.PRNGKey(0), cfg, mesh, optimizer=opt)
    step = make_train_step(cfg, mesh, optimizer=opt)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (4, 33), 0, cfg.vocab_size, jnp.int32
    )

    # ema starts as a copy of the init params
    init_wq = np.asarray(state.params["layers"]["wq"])
    np.testing.assert_array_equal(
        np.asarray(ema_params(state)["layers"]["wq"]), init_wq
    )

    # two steps: ema == d*(d*p0 + (1-d)*p1) + (1-d)*p2
    manual = init_wq
    for _ in range(2):
        state, _ = step(state, tokens)
        manual = decay * manual + (1 - decay) * np.asarray(
            state.params["layers"]["wq"]
        )
    got = np.asarray(ema_params(state)["layers"]["wq"])
    np.testing.assert_allclose(got, manual, rtol=1e-5, atol=1e-7)

    # the ema leaf inherits the param sharding (it mirrors the tree)
    ema_wq = ema_params(state)["layers"]["wq"]
    assert ema_wq.sharding.spec == state.params["layers"]["wq"].sharding.spec

    # checkpoint roundtrip preserves the shadow
    save_checkpoint(str(tmp_path), int(state.step), state)
    abstract = abstract_train_state(
        jax.random.PRNGKey(0), cfg, mesh, optimizer=opt
    )
    restored = restore_checkpoint(str(tmp_path), abstract)
    np.testing.assert_allclose(
        np.asarray(ema_params(restored)["layers"]["wq"]), got,
        rtol=0, atol=0,
    )

    # params-only restore can surface the EMA shadow (what serving
    # --use-ema does): same shape/sharding as params, moments on disk
    from containerpilot_tpu.parallel import restore_params

    got_params, got_step = restore_params(str(tmp_path), abstract)
    ema_restored = restore_params(
        str(tmp_path), abstract, prefer_ema=True
    )
    got_ema, ema_step = ema_restored
    # .ema reports what the restore ACTUALLY returned (evaluate's
    # "ema" report field comes from here, not a metadata re-probe)
    assert ema_restored.ema is True
    assert restore_params(str(tmp_path), abstract).ema is False
    assert int(got_step) == int(ema_step) == int(state.step)
    np.testing.assert_allclose(
        np.asarray(got_ema["layers"]["wq"]), got, rtol=0, atol=0
    )
    # the ema shadow differs from the raw params after training
    assert not np.allclose(
        np.asarray(got_ema["layers"]["wq"]),
        np.asarray(got_params["layers"]["wq"]),
    )

    # prefer_ema on an EMA-less checkpoint falls back to raw params
    plain = init_train_state(jax.random.PRNGKey(0), cfg, mesh)
    plain_step = make_train_step(cfg, mesh)
    plain, _ = plain_step(plain, tokens)
    save_checkpoint(str(tmp_path / "plain"), 1, plain)
    plain_abstract = abstract_train_state(jax.random.PRNGKey(0), cfg, mesh)
    fallback_restored = restore_params(
        str(tmp_path / "plain"), plain_abstract, prefer_ema=True
    )
    fallback, _ = fallback_restored
    assert fallback_restored.ema is False  # honest: raw params came back
    np.testing.assert_allclose(
        np.asarray(fallback["layers"]["wq"]),
        np.asarray(plain.params["layers"]["wq"]),
        rtol=0, atol=0,
    )

    # a plain state has no ema
    assert ema_params(plain) is None

    with pytest.raises(ValueError, match="decay"):
        with_ema(make_optimizer(1e-2), 1.5)


def test_inference_server_prefix_cache(run):
    """Prefix KV reuse: a second request sharing a long prompt prefix
    hits the cache, reuses most of the prefill, and produces EXACTLY
    the same tokens as an uncached server; LRU bounds the entries."""
    import urllib.request

    from containerpilot_tpu.models.transformer import init_params
    from containerpilot_tpu.workload.serve import InferenceServer

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=128, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    cached = InferenceServer(
        cfg, params, "127.0.0.1", 0, max_len=128,
        prefix_cache_entries=2,
    )
    plain = InferenceServer(cfg, params, "127.0.0.1", 0, max_len=128)

    shared = list(range(1, 41))  # 40-token shared history
    turn2 = shared + [50, 51, 52]
    other = [9] * 40

    def fetch(server, body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/generate",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read().decode())["tokens"]

    async def scenario():
        import asyncio

        await cached.run()
        await plain.run()
        loop = asyncio.get_event_loop()

        async def gen(server, toks, **kw):
            body = {"tokens": [toks], "max_new_tokens": 8, **kw}
            return await loop.run_in_executor(
                None, lambda: fetch(server, body)
            )

        r1c = await gen(cached, shared)
        r1p = await gen(plain, shared)
        r2c = await gen(cached, turn2)   # shares the 40-token prefix
        r2p = await gen(plain, turn2)
        # sampled request through the prefix path too (same seed)
        r3c = await gen(cached, turn2, temperature=0.8, seed=7)
        r3p = await gen(plain, turn2, temperature=0.8, seed=7)
        # a third distinct prompt evicts the oldest entry (LRU cap 2)
        await gen(cached, other)
        stats = dict(cached.prefix_cache.stats)
        n_entries = len(cached.prefix_cache)
        await cached.stop()
        await plain.stop()
        return r1c, r1p, r2c, r2p, r3c, r3p, stats, n_entries

    import json

    r1c, r1p, r2c, r2p, r3c, r3p, stats, n_entries = run(
        scenario(), timeout=180
    )
    assert r1c == r1p, "cold-path output must match the uncached server"
    assert r2c == r2p, "prefix-hit output must match the uncached server"
    assert r3c == r3p, "sampled prefix-hit must match (same seed)"
    assert stats["hits"] >= 2, stats
    assert stats["tokens_reused"] >= 40, stats
    assert n_entries == 2  # LRU evicted down to the cap


def test_generate_with_prefix_hit_honors_prefill_chunk():
    """The STANDALONE prefix path (generate_with_prefix) routes a
    long cached-hit suffix through the shared reuse_admission /
    extend_pieces protocol, so the documented O(prefill_chunk)
    activation bound covers it like the slot-engine paths — with
    byte-identical output to the unchunked server, and hit/miss
    stats counted exactly once (the refactor must not double-count
    misses)."""
    from types import SimpleNamespace

    import containerpilot_tpu.models.decode as dec
    from containerpilot_tpu.models.transformer import init_params
    from containerpilot_tpu.workload.serve_prefix import (
        PrefixCache,
        generate_with_prefix,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=128, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)

    def srv(prefill_chunk):
        return SimpleNamespace(
            cfg=cfg, params=params, max_len=128,
            prefill_chunk=prefill_chunk,
            prefix_cache=PrefixCache(4),
            batch_stats={"calls": 0, "rows": 0},
        )

    pieces = []
    real_pieces = dec.extend_pieces

    def counting_pieces(params_, cache, suffix, cfg_, chunk_len):
        pieces.append((int(suffix.shape[1]), int(chunk_len)))
        return real_pieces(params_, cache, suffix, cfg_, chunk_len)

    dec.extend_pieces = counting_pieces
    try:
        shared = list(range(1, 41))       # 40-token history
        turn2 = shared + [50] * 24        # 24-token suffix > chunk 8
        outs = {}
        hit_pieces = {}
        for name, chunk_len in (("plain", 0), ("chunked", 8)):
            s = srv(chunk_len)
            cold = generate_with_prefix(
                s, shared, 8, 0.0, 0, 0.0, -1, 0
            )
            pieces.clear()  # isolate the HIT call's extend pieces
            hit = generate_with_prefix(
                s, turn2, 8, 0.0, 0, 0.0, -1, 0
            )
            hit_pieces[name] = list(pieces)
            outs[name] = [cold, hit]
            assert s.prefix_cache.stats["misses"] == 1, (
                s.prefix_cache.stats
            )
            assert s.prefix_cache.stats["hits"] == 1, (
                s.prefix_cache.stats
            )
            # suffix 24 buckets to 32 (BUCKET=16), so 32 of the 40
            # matched tokens are reused and 32 re-extend
            assert s.prefix_cache.stats["tokens_reused"] == 32
    finally:
        dec.extend_pieces = real_pieces
    assert outs["plain"] == outs["chunked"]
    # the chunked server's hit actually took the bounded-piece path;
    # the unchunked server's hit stayed on the one-shot extend
    assert hit_pieces == {"plain": [], "chunked": [(32, 8)]}


def test_chunked_prefill_matches_prefill():
    """Streaming the prompt through decode_chunk pieces must produce
    the same cache and last-position logits as one-shot prefill —
    dense, GQA, ragged final chunk, and windowed ring."""
    from containerpilot_tpu.models.decode import chunked_prefill, prefill

    for kw in (
        {},
        {"n_kv_heads": 2},
        {"window": 8},
    ):
        cfg = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=128,
            max_seq_len=64, dtype=jnp.float32, flash_min_seq=0, **kw
        )
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (2, 23), 0, cfg.vocab_size, jnp.int32
        )  # 23 = 3 chunks of 7 + ragged 2
        ref_logits, ref_cache = prefill(params, tokens, cfg, 64)
        got_logits, got_cache = chunked_prefill(
            params, tokens, cfg, 64, chunk_len=7
        )
        np.testing.assert_allclose(
            np.asarray(got_logits), np.asarray(ref_logits),
            rtol=2e-3, atol=2e-3, err_msg=str(kw),
        )
        np.testing.assert_allclose(
            np.asarray(got_cache["k"]), np.asarray(ref_cache["k"]),
            rtol=1e-4, atol=1e-5, err_msg=str(kw),
        )
        assert int(got_cache["pos"]) == int(ref_cache["pos"]) == 23
        # decode continues identically from either cache
        from containerpilot_tpu.models.decode import decode_step

        la, _ = decode_step(params, got_cache, tokens[:, 0], cfg)
        lb, _ = decode_step(params, ref_cache, tokens[:, 0], cfg)
        np.testing.assert_allclose(
            np.asarray(la), np.asarray(lb), rtol=2e-3, atol=2e-3
        )
    with pytest.raises(ValueError, match="chunk_len"):
        chunked_prefill(params, tokens, cfg, 64, chunk_len=0)


def test_beam_search_width1_equals_greedy_and_exhaustive_optimum():
    """beam_width=1 reproduces greedy generate exactly; a beam wide
    enough to be exhaustive finds the brute-force argmax sequence."""
    from containerpilot_tpu.models.beam import beam_search
    from containerpilot_tpu.models.decode import generate
    from containerpilot_tpu.models.transformer import forward

    cfg = TransformerConfig(
        vocab_size=8, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=32, dtype=jnp.float32, flash_min_seq=0,
    )
    params = init_params(jax.random.PRNGKey(3), cfg)
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)

    greedy = np.asarray(generate(params, prompt, cfg, 4, 32))[0]
    b1, _ = beam_search(params, prompt, cfg, 4, 32, beam_width=1)
    np.testing.assert_array_equal(np.asarray(b1), greedy)

    # exhaustive optimum over 2 steps: beam_width == vocab keeps every
    # possible first token, so no prefix of the best pair is pruned
    best_beam, best_score = beam_search(
        params, prompt, cfg, 2, 32, beam_width=8
    )

    def seq_logprob(cont):
        toks = jnp.asarray([[1, 2, 3] + list(cont)], jnp.int32)
        logits = forward(params, toks, cfg)
        logp = jax.nn.log_softmax(
            logits.astype(jnp.float32), axis=-1
        )
        return sum(
            float(logp[0, 2 + i, cont[i]]) for i in range(len(cont))
        )

    brute = max(
        ((a, b) for a in range(8) for b in range(8)),
        key=seq_logprob,
    )
    assert tuple(np.asarray(best_beam)) == brute
    np.testing.assert_allclose(best_score, seq_logprob(brute), rtol=1e-5)


def test_beam_search_eos_and_validation():
    """Finished beams freeze (pad after eos, score keeps competing);
    invalid arguments fail loudly."""
    from containerpilot_tpu.models.beam import beam_search

    cfg = TransformerConfig(
        vocab_size=16, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=32, dtype=jnp.float32, flash_min_seq=0,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jnp.asarray([[1, 2]], jnp.int32)
    # beam_width=1 follows the greedy path exactly, so declaring the
    # greedy second token as eos GUARANTEES the freeze logic fires
    from containerpilot_tpu.models.decode import generate

    greedy = list(np.asarray(generate(params, prompt, cfg, 6, 32))[0])
    eos = int(greedy[1])
    toks, _ = beam_search(
        params, prompt, cfg, 6, 32, beam_width=1, eos_id=eos, pad_id=0
    )
    toks = list(np.asarray(toks))
    assert eos in toks, (toks, greedy)
    after = toks[toks.index(eos) + 1:]
    # eos fires by step 2 at the latest, so pads definitely follow
    assert len(after) >= 4 and all(t == 0 for t in after), toks
    with pytest.raises(ValueError, match="beam_width"):
        beam_search(params, prompt, cfg, 4, 32, beam_width=0)
    with pytest.raises(ValueError, match="one prompt"):
        beam_search(
            params, jnp.ones((2, 3), jnp.int32), cfg, 4, 32
        )
    with pytest.raises(ValueError, match="sliding-window"):
        import dataclasses

        beam_search(
            params, prompt, dataclasses.replace(cfg, window=8), 4, 32
        )


def test_inference_server_beam_search(run):
    """/v1/generate beam_width: beam-1 equals greedy over HTTP; wider
    beams return a (length-trimmed) deterministic result; invalid
    combinations 422."""
    import urllib.error
    import urllib.request

    from containerpilot_tpu.models.transformer import init_params
    from containerpilot_tpu.workload.serve import InferenceServer

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    server = InferenceServer(cfg, params, "127.0.0.1", 0, max_len=64)

    def fetch(body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/generate",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return resp.status, json.loads(resp.read().decode())
        except urllib.error.HTTPError as exc:
            return exc.code, exc.read().decode()

    async def scenario():
        import asyncio

        await server.run()
        loop = asyncio.get_event_loop()

        async def gen(body):
            return await loop.run_in_executor(None, lambda: fetch(body))

        base = {"tokens": [[1, 2, 3]], "max_new_tokens": 6}
        greedy = await gen(base)
        b1 = await gen({**base, "beam_width": 1})
        b4a = await gen({**base, "beam_width": 4})
        b4b = await gen({**base, "beam_width": 4})
        bad = await gen({**base, "beam_width": 4, "temperature": 0.7})
        await server.stop()
        return greedy, b1, b4a, b4b, bad

    import json

    greedy, b1, b4a, b4b, bad = run(scenario(), timeout=180)
    assert greedy[0] == b1[0] == 200
    assert b1[1]["tokens"] == greedy[1]["tokens"]
    assert b4a[0] == 200 and b4a[1] == b4b[1]  # deterministic
    assert bad[0] == 422 and "deterministic" in bad[1]


def test_async_checkpoint_commits_and_restores(tmp_path):
    """save_checkpoint(wait=False) returns before the disk commit but
    captures the state at call time: stepping (and donating) right
    after the call cannot corrupt the write, and after
    wait_for_checkpoints the restore equals the saved-step state."""
    from containerpilot_tpu.parallel import (
        abstract_train_state,
        restore_checkpoint,
        save_checkpoint,
        wait_for_checkpoints,
    )

    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq_len=64, dtype=jnp.float32,
    )
    mesh = make_mesh(jax.devices()[:8])
    state = init_train_state(jax.random.PRNGKey(0), cfg, mesh)
    step = make_train_step(cfg, mesh)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (4, 33), 0, cfg.vocab_size, jnp.int32
    )
    state, _ = step(state, tokens)
    saved_wq = np.asarray(state.params["layers"]["wq"]).copy()
    save_checkpoint(str(tmp_path), 1, state, wait=False)
    # keep training immediately — the donated buffers get overwritten
    # while the background write is (possibly) still in flight
    for _ in range(3):
        state, _ = step(state, tokens)
    assert not np.allclose(
        np.asarray(state.params["layers"]["wq"]), saved_wq
    )
    wait_for_checkpoints()
    abstract = abstract_train_state(jax.random.PRNGKey(0), cfg, mesh)
    restored = restore_checkpoint(str(tmp_path), abstract)
    assert int(restored.step) == 1
    np.testing.assert_array_equal(
        np.asarray(restored.params["layers"]["wq"]), saved_wq
    )


def test_kv_int8_cache_decode_parity():
    """int8 KV cache: half the bytes, decode stays within quantization
    tolerance of the f32-cache path — dense, GQA, windowed ring, and
    chunked decode; greedy token-level agreement end-to-end."""
    from containerpilot_tpu.models.decode import (
        decode_chunk,
        decode_step,
        generate,
        prefill,
    )
    import dataclasses

    for kw in ({}, {"n_kv_heads": 2}, {"window": 8}):
        cfg = TransformerConfig(
            vocab_size=64, d_model=64, n_heads=4, n_layers=2, d_ff=128,
            max_seq_len=64, dtype=jnp.float32, flash_min_seq=0, **kw
        )
        cfg_q = dataclasses.replace(cfg, kv_int8=True)
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (2, 24), 0, cfg.vocab_size, jnp.int32
        )
        ref_logits, ref_cache = prefill(params, tokens[:, :10], cfg, 48)
        q_logits, q_cache = prefill(params, tokens[:, :10], cfg_q, 48)
        assert q_cache["k"].dtype == jnp.int8
        assert "k_scale" in q_cache
        # bytes: int8 k/v + f32 scales ~ half the f32 k/v
        f32_bytes = ref_cache["k"].nbytes + ref_cache["v"].nbytes
        q_bytes = sum(
            q_cache[n].nbytes for n in
            ("k", "v", "k_scale", "v_scale")
        )
        assert q_bytes < f32_bytes / 2 + 1
        np.testing.assert_allclose(
            np.asarray(q_logits), np.asarray(ref_logits),
            rtol=0.05, atol=0.05, err_msg=str(kw),
        )
        # chunked decode through the quantized cache
        la, ca = decode_chunk(params, ref_cache, tokens[:, 10:14], cfg)
        lb, cb = decode_chunk(params, q_cache, tokens[:, 10:14], cfg_q)
        np.testing.assert_allclose(
            np.asarray(lb), np.asarray(la), rtol=0.08, atol=0.08,
            err_msg=str(kw),
        )
        for i in range(14, 20):
            la, ca = decode_step(params, ca, tokens[:, i], cfg)
            lb, cb = decode_step(params, cb, tokens[:, i], cfg_q)
            np.testing.assert_allclose(
                np.asarray(lb), np.asarray(la), rtol=0.1, atol=0.1,
                err_msg=f"{kw} position {i}",
            )
        # greedy generations agree token-for-token on this scale of
        # model (logit gaps dwarf the quantization noise)
        ga = generate(params, tokens[:, :10], cfg, 8, 48)
        gb = generate(params, tokens[:, :10], cfg_q, 8, 48)
        np.testing.assert_array_equal(
            np.asarray(ga), np.asarray(gb), err_msg=str(kw)
        )


def test_inference_server_text_completions(run):
    """The text surface (--text): /v1/completions encodes the prompt
    through the byte tokenizer, decodes generated ids back to text,
    and agrees exactly with the token-level /v1/generate path."""
    import urllib.error
    import urllib.request

    from containerpilot_tpu.models.transformer import init_params
    from containerpilot_tpu.workload.serve import InferenceServer
    from containerpilot_tpu.workload.text import ByteTokenizer

    cfg = TransformerConfig(
        vocab_size=512, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    server = InferenceServer(
        cfg, params, "127.0.0.1", 0, max_len=64, text=True
    )
    tok = ByteTokenizer(cfg.vocab_size)

    def fetch(path, body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}{path}",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return resp.status, json.loads(resp.read().decode())
        except urllib.error.HTTPError as exc:
            return exc.code, exc.read().decode()

    async def scenario():
        import asyncio

        await server.run()
        loop = asyncio.get_event_loop()
        comp = await loop.run_in_executor(
            None,
            lambda: fetch(
                "/v1/completions",
                {"prompt": "hi", "max_new_tokens": 6},
            ),
        )
        # token-level equivalent: same encoding, explicit EOS default
        gen = await loop.run_in_executor(
            None,
            lambda: fetch(
                "/v1/generate",
                {"tokens": [tok.encode("hi")], "max_new_tokens": 6,
                 "eos_id": tok.EOS},
            ),
        )
        bad = await loop.run_in_executor(
            None, lambda: fetch("/v1/completions", {"prompt": ""})
        )
        too_long = await loop.run_in_executor(
            None,
            lambda: fetch("/v1/completions",
                          {"prompt": "x", "max_new_tokens": 999}),
        )
        # this server has no --slots: stream must 422 cleanly, not
        # hand an SSE client a plain 200 body it would hang parsing
        streamed = await loop.run_in_executor(
            None,
            lambda: fetch("/v1/completions",
                          {"prompt": "x", "stream": True}),
        )
        await server.stop()
        return comp, gen, bad, too_long, streamed

    import json

    comp, gen, bad, too_long, streamed = run(scenario(), timeout=120)
    assert comp[0] == 200, comp
    assert gen[0] == 200, gen
    assert comp[1]["tokens"] == gen[1]["tokens"][0]
    assert comp[1]["text"] == tok.decode(comp[1]["tokens"])
    assert bad[0] == 422
    assert too_long[0] == 422
    assert streamed[0] == 422 and "--slots" in streamed[1]


def test_serve_text_requires_byte_vocab():
    """--text with a vocab too small for the byte tokenizer fails at
    construction, not as request-time 500s."""
    from containerpilot_tpu.models.transformer import init_params
    from containerpilot_tpu.workload.serve import InferenceServer

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    with pytest.raises(ValueError, match="vocab_size >= 259"):
        InferenceServer(
            cfg, params, "127.0.0.1", 0, max_len=32, text=True
        )


def test_serve_cli_text_flag():
    """The --text flag exists and routes into InferenceServer."""
    from containerpilot_tpu.workload.serve_cli import build_arg_parser

    args = build_arg_parser().parse_args(["--text", "--vocab", "512"])
    assert args.text is True and args.vocab == 512
    assert build_arg_parser().parse_args([]).text is False


def test_remat_policies_equivalent():
    """remat=True (full), remat="dots" (keep matmul outputs), and
    remat=False (plus the "full"/"none" string aliases) trade memory
    for recompute only — loss and grads must agree to tight numerical
    tolerance across policies."""
    import numpy as np

    from containerpilot_tpu.models.transformer import (
        TransformerConfig,
        init_params,
        loss_fn,
    )

    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 17), 0, 64, jnp.int32
    )
    results = {}
    for remat in (True, "dots", False, "full", "none"):
        cfg = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
            max_seq_len=16, dtype=jnp.float32, remat=remat,
        )
        params = init_params(jax.random.PRNGKey(0), cfg)
        loss, grads = jax.jit(
            jax.value_and_grad(lambda p: loss_fn(p, tokens, cfg))
        )(params)
        results[str(remat)] = (
            float(loss),
            [np.asarray(g) for g in jax.tree.leaves(grads)],
        )
    # the string aliases must be exact synonyms of their booleans
    for alias, boolean in (("full", "True"), ("none", "False")):
        assert results[alias][0] == results[boolean][0]
        for a, b in zip(results[alias][1], results[boolean][1]):
            np.testing.assert_array_equal(a, b)
    base_loss, base_grads = results["True"]
    for name, (loss, grads) in results.items():
        np.testing.assert_allclose(loss, base_loss, rtol=1e-6, err_msg=name)
        assert len(grads) == len(base_grads)
        for got, want in zip(grads, base_grads):
            np.testing.assert_allclose(
                got, want, rtol=1e-5, atol=1e-6, err_msg=name
            )


def test_remat_invalid_value_rejected_at_construction():
    with pytest.raises(ValueError, match="remat"):
        TransformerConfig(
            vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
            max_seq_len=16, remat="Dots",
        )


def test_tensor_parallel_generate_parity():
    """Serving TP: generate with params sharded model-parallel over
    the 8-device CPU mesh matches the single-device output exactly —
    greedy and seeded-sampled. XLA inserts the collectives; the decode
    scan, KV cache, and sampling all ride the sharding."""
    import numpy as np

    from containerpilot_tpu.models.decode import generate
    from containerpilot_tpu.models.transformer import init_params
    from containerpilot_tpu.parallel import (
        MeshPlan,
        make_mesh,
        shard_params,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=64, n_heads=8, n_layers=2, d_ff=128,
        max_seq_len=32, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh(jax.devices()[:8], plan=MeshPlan(data=1, model=8))
    sharded = shard_params(params, mesh, cfg)
    prompt = jax.random.randint(
        jax.random.PRNGKey(7), (2, 6), 0, cfg.vocab_size, jnp.int32
    )
    for kwargs in (
        {"temperature": 0.0},
        {"temperature": 0.8, "rng": jax.random.PRNGKey(3), "top_k": 8},
    ):
        single = generate(
            params, prompt, cfg, max_new_tokens=8, max_len=32, **kwargs
        )
        tp = generate(
            sharded, prompt, cfg, max_new_tokens=8, max_len=32, **kwargs
        )
        np.testing.assert_array_equal(
            np.asarray(single), np.asarray(tp), err_msg=str(kwargs)
        )


def test_tensor_parallel_moe_generate_parity():
    """Expert-parallel serving: an MoE model's experts shard over the
    model axis with the rest of the TP rules, and sharded decode
    byte-matches single-device — the ep x tp serving composition."""
    import numpy as np

    from containerpilot_tpu.models.decode import generate
    from containerpilot_tpu.models.transformer import init_params
    from containerpilot_tpu.parallel import (
        MeshPlan,
        make_mesh,
        shard_params,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq_len=32, dtype=jnp.float32, moe_experts=4,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh(jax.devices()[:4], plan=MeshPlan(data=1, model=4))
    sharded = shard_params(params, mesh, cfg)
    prompt = jax.random.randint(
        jax.random.PRNGKey(11), (2, 5), 0, cfg.vocab_size, jnp.int32
    )
    single = generate(params, prompt, cfg, max_new_tokens=6, max_len=32)
    ep = generate(sharded, prompt, cfg, max_new_tokens=6, max_len=32)
    np.testing.assert_array_equal(np.asarray(single), np.asarray(ep))


def test_inference_server_reports_mesh(run):
    """/v1/model surfaces the device mesh TP-sharded params live on,
    and serving works end-to-end on sharded params."""
    import json
    import urllib.request

    from containerpilot_tpu.models.transformer import init_params
    from containerpilot_tpu.parallel import (
        MeshPlan,
        make_mesh,
        shard_params,
    )
    from containerpilot_tpu.workload.serve import InferenceServer

    cfg = TransformerConfig(
        vocab_size=64, d_model=64, n_heads=8, n_layers=1, d_ff=128,
        max_seq_len=32, dtype=jnp.float32,
    )
    mesh = make_mesh(jax.devices()[:8], plan=MeshPlan(data=1, model=8))
    params = shard_params(
        init_params(jax.random.PRNGKey(0), cfg), mesh, cfg
    )
    server = InferenceServer(cfg, params, "127.0.0.1", 0, max_len=32)

    def fetch(path, body=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}{path}",
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"} if body else {},
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read().decode())

    async def scenario():
        import asyncio

        await server.run()
        loop = asyncio.get_event_loop()
        info = await loop.run_in_executor(
            None, lambda: fetch("/v1/model")
        )
        gen = await loop.run_in_executor(
            None,
            lambda: fetch(
                "/v1/generate",
                {"tokens": [[1, 2, 3]], "max_new_tokens": 4},
            ),
        )
        await server.stop()
        return info, gen

    info, gen = run(scenario())
    assert info["mesh"] == {"data": 1, "model": 8}
    assert len(gen["tokens"][0]) == 4


def test_compile_cache_env_populates_and_reuses(tmp_path):
    """CONTAINERPILOT_COMPILE_CACHE: a workload CLI run persists its
    compiled programs, and a fresh process reads them back (cache-hit
    logging on) — the reincarnation-warmup lever the supervisor's
    restart story leans on."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    wrapper = tmp_path / "train_cpu.py"
    wrapper.write_text(
        "import sys\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        f"sys.path.insert(0, {repo!r})\n"
        "from containerpilot_tpu.workload.train import main\n"
        "sys.exit(main())\n"
    )
    cache = tmp_path / "xla-cache"
    argv = [
        sys.executable, "-u", str(wrapper),
        "--steps", "2", "--batch", "2", "--seq-len", "16",
        "--d-model", "32", "--n-layers", "1", "--n-heads", "2",
        "--vocab", "64",
    ]
    env = dict(os.environ, CONTAINERPILOT_COMPILE_CACHE=str(cache))
    env.pop("XLA_FLAGS", None)
    # the dedicated cache dir must be the ONLY cache in play
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    env.pop("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", None)
    first = subprocess.run(
        argv, env=env, capture_output=True, text=True, timeout=240,
    )
    assert first.returncode == 0, first.stdout[-2000:] + first.stderr[-2000:]
    entries = list(cache.iterdir())
    assert entries, "compile cache never populated"
    # second process must HIT the persisted entries, not just write new
    env["JAX_EXPLAIN_CACHE_MISSES"] = "true"
    before = {e.name for e in entries}
    second = subprocess.run(
        argv, env=env, capture_output=True, text=True, timeout=240,
    )
    assert second.returncode == 0, second.stderr[-2000:]
    after = {e.name for e in cache.iterdir()}
    assert before <= after  # nothing evicted; hits don't rewrite


def test_continuous_deployment_reload_serves_new_checkpoint(tmp_path):
    """The documented continuous-deployment loop
    (examples/serving-pod.json5): ONE supervisor runs a trainer
    writing checkpoints to a shared dir alongside an inference server
    that started before any checkpoint existed; when training lands,
    a control-socket reload reincarnates the server, which restores
    the new weights — scores for a fixed input change, and the
    supervisor log names the served step."""
    import json
    import os
    import signal
    import subprocess
    import sys
    import time as time_mod
    import urllib.request

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def wrapper(name, module):
        path = tmp_path / name
        path.write_text(
            "import sys\n"
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            f"sys.path.insert(0, {repo!r})\n"
            f"from containerpilot_tpu.workload.{module} import main\n"
            "sys.exit(main())\n"
        )
        return str(path)

    import socket as socket_mod

    with socket_mod.socket() as s:
        s.bind(("127.0.0.1", 0))
        http_port = s.getsockname()[1]
    ck = tmp_path / "ck"
    ctl = tmp_path / "cp.socket"
    model_flags = ["--d-model", "32", "--n-layers", "1",
                   "--n-heads", "2", "--vocab", "64"]
    config = {
        "stopTimeout": "5s",
        "control": {"socket": str(ctl)},
        "logging": {"level": "INFO", "format": "default",
                    "output": "stdout"},
        "jobs": [
            {
                "name": "trainer",
                # gated on a file the TEST creates after scoring the
                # pre-training weights — deterministic ordering on a
                # box where job startup times race
                "exec": ["/bin/sh", "-c",
                         "while [ ! -f "
                         + __import__("shlex").quote(
                             str(tmp_path / "train-gate")
                         )
                         + " ]; do sleep 0.2; done; exec "
                         + __import__("shlex").join(
                             [sys.executable, "-u",
                              wrapper("train_cpu.py", "train"),
                              "--steps", "4", "--batch", "2",
                              "--seq-len", "16",
                              "--checkpoint-dir", str(ck),
                              "--checkpoint-every", "1"]
                             + model_flags
                         )],
                "restarts": "never",
            },
            {
                "name": "server",
                "exec": [sys.executable, "-u",
                         wrapper("serve_cpu.py", "serve"),
                         "--host", "127.0.0.1",
                         "--port", str(http_port),
                         "--max-len", "32",
                         "--checkpoint-dir", str(ck)] + model_flags,
                "restarts": "never",
            },
        ],
    }
    cfg_path = tmp_path / "cd.json5"
    cfg_path.write_text(json.dumps(config))
    env = dict(os.environ, PYTHONPATH=repo)
    env.pop("XLA_FLAGS", None)
    log_fh = open(tmp_path / "sup.log", "w")
    sup = subprocess.Popen(
        [sys.executable, "-m", "containerpilot_tpu",
         "-config", str(cfg_path)],
        cwd=repo, env=env, stdout=log_fh, stderr=subprocess.STDOUT,
    )

    def score():
        req = urllib.request.Request(
            f"http://127.0.0.1:{http_port}/v1/score",
            data=json.dumps({"tokens": [[1, 2, 3, 4]]}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read().decode())

    def wait_health(deadline_s):
        deadline = time_mod.monotonic() + deadline_s
        while True:
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{http_port}/health", timeout=2
                )
                return
            except Exception:
                assert sup.poll() is None, (
                    tmp_path / "sup.log"
                ).read_text()[-3000:]
                assert time_mod.monotonic() < deadline, (
                    tmp_path / "sup.log"
                ).read_text()[-3000:]
                time_mod.sleep(0.5)

    try:
        wait_health(300)
        before = score()  # fresh-init weights (training is gated off)
        (tmp_path / "train-gate").write_text("go")
        from containerpilot_tpu.parallel import latest_step

        deadline = time_mod.monotonic() + 300
        while (latest_step(str(ck)) or 0) < 4:
            assert time_mod.monotonic() < deadline, (
                tmp_path / "sup.log"
            ).read_text()[-3000:]
            time_mod.sleep(0.5)

        # the documented CD step: reload; the new generation's server
        # restores the freshly trained checkpoint
        from containerpilot_tpu.client import ControlClient

        ControlClient(str(ctl)).reload()
        # the OLD server keeps draining (and answering) for up to
        # stopTimeout — don't race it: wait for the NEW generation's
        # own markers (it restored the checkpoint, then bound the
        # port — which it can only do once the old one released it)
        deadline = time_mod.monotonic() + 300
        while True:
            log_text = (tmp_path / "sup.log").read_text()
            if (
                "serving checkpoint step 4" in log_text
                and log_text.count("accepting traffic") >= 2
            ):
                break
            assert sup.poll() is None, log_text[-3000:]
            assert time_mod.monotonic() < deadline, log_text[-3000:]
            time_mod.sleep(0.5)
        wait_health(300)
        after = score()
        assert after["logprobs"] != before["logprobs"], (
            "reload did not swap weights"
        )
    finally:
        if sup.poll() is None:
            sup.send_signal(signal.SIGTERM)
            try:
                sup.wait(timeout=60)
            except subprocess.TimeoutExpired:
                sup.kill()
        log_fh.close()


def test_trainer_graceful_preemption(tmp_path):
    """SIGTERM mid-run: the trainer finishes the in-flight step,
    checkpoints, exits 0; a restart resumes from that exact step —
    the TPU-maintenance / supervisor-stop path."""
    import json
    import os
    import signal
    import subprocess
    import sys
    import time as time_mod

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    wrapper = tmp_path / "train_cpu.py"
    wrapper.write_text(
        "import sys\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        f"sys.path.insert(0, {repo!r})\n"
        "from containerpilot_tpu.workload.train import main\n"
        "sys.exit(main())\n"
    )
    ckpt = tmp_path / "ckpt"
    progress = tmp_path / "progress.json"
    argv = [
        sys.executable, "-u", str(wrapper),
        "--steps", "500000", "--batch", "2", "--seq-len", "16",
        "--d-model", "32", "--n-layers", "1", "--n-heads", "2",
        "--vocab", "64",
        "--checkpoint-dir", str(ckpt), "--checkpoint-every", "100000",
        "--progress-file", str(progress),
    ]
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    proc = subprocess.Popen(
        argv, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    try:
        deadline = time_mod.monotonic() + 240
        while True:
            if progress.exists():
                try:
                    if json.loads(progress.read_text())["step"] >= 5:
                        break
                except (ValueError, KeyError):
                    pass
            assert time_mod.monotonic() < deadline, "trainer never progressed"
            assert proc.poll() is None, proc.stdout.read()[-2000:]
            time_mod.sleep(0.05)
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, out[-2000:]
    assert "preempted: checkpoint saved at step" in out, out[-2000:]

    from containerpilot_tpu.parallel import latest_step

    saved = latest_step(str(ckpt))
    assert saved is not None and saved >= 5
    # the preemption message names the saved step — the save cannot be
    # explained by the (100000-step) periodic cadence alone
    assert f"checkpoint saved at step {saved}" in out, out[-2000:]

    # restart resumes from exactly the preemption step and completes
    finish = subprocess.run(
        argv[:argv.index("500000")] + [str(saved + 3)]
        + argv[argv.index("500000") + 1:],
        env=env, capture_output=True, text=True, timeout=240,
    )
    assert finish.returncode == 0, finish.stdout[-2000:]
    assert f"resumed from checkpoint at step {saved}" in finish.stdout, (
        finish.stdout[-2000:]
    )


@pytest.mark.parametrize("seq", [16, 17])  # 17: chunk-padding path
def test_chunked_loss_matches_whole_logits(seq):
    """loss_chunk streams the vocab projection in pieces; loss and
    grads must match the whole-logits loss to f32 tolerance, including
    when the sequence does not divide by the chunk."""
    import dataclasses

    base = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, seq + 1), 0, base.vocab_size,
        jnp.int32,
    )
    params = init_params(jax.random.PRNGKey(0), base)
    whole_loss, whole_grads = jax.jit(
        jax.value_and_grad(lambda p: loss_fn(p, tokens, base))
    )(params)
    chunked = dataclasses.replace(base, loss_chunk=8)
    c_loss, c_grads = jax.jit(
        jax.value_and_grad(lambda p: loss_fn(p, tokens, chunked))
    )(params)
    np.testing.assert_allclose(
        float(c_loss), float(whole_loss), rtol=1e-6
    )
    for got, want in zip(
        jax.tree.leaves(c_grads), jax.tree.leaves(whole_grads)
    ):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=1e-6
        )


def test_chunked_loss_matches_with_moe_aux():
    import dataclasses

    base = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=32, dtype=jnp.float32, moe_experts=2,
    )
    tokens = jax.random.randint(
        jax.random.PRNGKey(2), (2, 13), 0, base.vocab_size, jnp.int32
    )
    params = init_params(jax.random.PRNGKey(0), base)
    whole = float(jax.jit(lambda p: loss_fn(p, tokens, base))(params))
    chunked = dataclasses.replace(base, loss_chunk=4)
    got = float(jax.jit(lambda p: loss_fn(p, tokens, chunked))(params))
    np.testing.assert_allclose(got, whole, rtol=1e-6)


def test_generate_stop_sequences(run):
    """'stop' trims at the earliest stop-sequence occurrence,
    excluding the stop itself; invalid specs 422."""
    import json
    import urllib.error
    import urllib.request

    from containerpilot_tpu.models.transformer import init_params
    from containerpilot_tpu.workload.serve import InferenceServer

    cfg = TransformerConfig(
        vocab_size=512, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    server = InferenceServer(
        cfg, params, "127.0.0.1", 0, max_len=64, text=True
    )

    def fetch(path, body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}{path}",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return resp.status, json.loads(resp.read().decode())
        except urllib.error.HTTPError as exc:
            return exc.code, exc.read().decode()

    async def scenario():
        import asyncio

        await server.run()
        loop = asyncio.get_event_loop()

        def go():
            # free-run greedy to learn the deterministic continuation
            _s, free = fetch(
                "/v1/generate",
                {"tokens": [[1, 2, 3]], "max_new_tokens": 8},
            )
            row = free["tokens"][0]
            # stop at the first token whose value hasn't occurred
            # before it: output = everything before that position
            k = next(
                (i for i in range(1, len(row))
                 if row[i] not in row[:i]),
                None,  # all-repeats continuation: nothing to stop on
            )
            if k is None:
                return row, None, (200, {"tokens": [row]}), \
                    (200, {"tokens": [row]}), 422, 422
            s1, stopped = fetch(
                "/v1/generate",
                {"tokens": [[1, 2, 3]], "max_new_tokens": 8,
                 "stop": [[row[k]]]},
            )
            # a stop that never occurs changes nothing
            s2, untouched = fetch(
                "/v1/generate",
                {"tokens": [[1, 2, 3]], "max_new_tokens": 8,
                 "stop": [[cfg.vocab_size - 1, cfg.vocab_size - 2]]},
            )
            s3, bad = fetch(
                "/v1/generate",
                {"tokens": [[1, 2, 3]], "max_new_tokens": 4,
                 "stop": [[]]},
            )
            s4, bad_type = fetch(
                "/v1/generate",
                {"tokens": [[1, 2, 3]], "max_new_tokens": 4,
                 "stop": "nope"},
            )
            return row, k, (s1, stopped), (s2, untouched), s3, s4

        out = await loop.run_in_executor(None, go)
        await server.stop()
        return out

    row, k, (s1, stopped), (s2, untouched), s3, s4 = run(scenario())
    if k is None:
        pytest.skip("greedy continuation has no first-unique token")
    assert s1 == 200 and stopped["tokens"][0] == row[:k]
    assert s2 == 200 and untouched["tokens"][0] == row
    assert s3 == 422 and s4 == 422


def test_completions_stop_strings(run):
    """The text surface takes stop STRINGS and excludes them."""
    import json
    import urllib.request

    from containerpilot_tpu.models.transformer import init_params
    from containerpilot_tpu.workload.serve import InferenceServer
    from containerpilot_tpu.workload.text import ByteTokenizer

    cfg = TransformerConfig(
        vocab_size=512, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    server = InferenceServer(
        cfg, params, "127.0.0.1", 0, max_len=64, text=True
    )
    tok = ByteTokenizer(cfg.vocab_size)

    def fetch(body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/completions",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read().decode())

    async def scenario():
        import asyncio

        await server.run()
        loop = asyncio.get_event_loop()

        def go():
            free = fetch({"prompt": "ab", "max_new_tokens": 6})
            # stop at the text of the 2nd+3rd generated bytes
            stop_text = tok.decode(free["tokens"][1:3])
            # only meaningful when the text round-trips to exactly
            # those ids (specials/out-of-range bytes are dropped by
            # decode and would test a DIFFERENT stop sequence)
            if (
                not stop_text
                or tok.encode(stop_text, bos=False)
                != free["tokens"][1:3]
            ):
                return free, None, None
            stopped = fetch(
                {"prompt": "ab", "max_new_tokens": 6,
                 "stop": stop_text}
            )
            return free, stop_text, stopped

        out = await loop.run_in_executor(None, go)
        await server.stop()
        return out

    free, stop_text, stopped = run(scenario())
    if stop_text is not None:
        assert stopped["tokens"] == free["tokens"][:1]
        assert stop_text not in stopped["text"]


def test_min_new_tokens_suppresses_early_eos():
    """min_new_tokens masks the eos logit for a row's first N samples
    on the compiled path — greedy AND sampled — so answers can be
    floored; min_new=0 leaves numerics bitwise-unchanged."""
    from containerpilot_tpu.models.decode import generate
    from containerpilot_tpu.models.transformer import init_params

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jnp.asarray([[3, 5, 7]], jnp.int32)

    baseline = np.asarray(generate(
        params, prompt, cfg, max_new_tokens=8, max_len=32
    ))[0]
    eos = int(baseline[1])  # would stop after 2 tokens

    zero = np.asarray(generate(
        params, prompt, cfg, max_new_tokens=8, max_len=32,
        min_new_tokens=0, eos_id=eos,
    ))[0]
    floored = np.asarray(generate(
        params, prompt, cfg, max_new_tokens=8, max_len=32,
        min_new_tokens=5, eos_id=eos,
    ))[0]
    # min_new=0: the early eos stands (token 1), pads follow
    assert zero[1] == eos
    # floored: samples 0..4 are eos-free by construction
    assert not (floored[:5] == eos).any()

    # sampled path too, per-row: row 0 floored, row 1 free
    two = jnp.asarray([[3, 5, 7], [3, 5, 7]], jnp.int32)
    out = np.asarray(generate(
        params, two, cfg, max_new_tokens=8, max_len=32,
        temperature=0.9, rng=jax.random.PRNGKey(5),
        eos_id=eos, min_new_tokens=[6, 0],
    ))
    assert not (out[0, :6] == eos).any()

    with pytest.raises(ValueError, match="min_new_tokens"):
        generate(
            params, prompt, cfg, max_new_tokens=4, max_len=32,
            min_new_tokens=9,
        )


def test_min_new_tokens_over_http(run):
    """The serving knob floors answers through the batcher path and
    422s out-of-range values."""
    import json
    import urllib.error
    import urllib.request

    from containerpilot_tpu.models.transformer import init_params
    from containerpilot_tpu.workload.serve import InferenceServer

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    server = InferenceServer(cfg, params, "127.0.0.1", 0, max_len=32)

    def fetch(body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/generate",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return resp.status, json.loads(resp.read().decode())
        except urllib.error.HTTPError as exc:
            return exc.code, exc.read().decode()

    async def scenario():
        import asyncio

        await server.run()
        loop = asyncio.get_event_loop()

        def go():
            _s, free = fetch(
                {"tokens": [[1, 2, 3]], "max_new_tokens": 8}
            )
            eos = free["tokens"][0][1]
            s1, stopped = fetch(
                {"tokens": [[1, 2, 3]], "max_new_tokens": 8,
                 "eos_id": eos}
            )
            s2, floored = fetch(
                {"tokens": [[1, 2, 3]], "max_new_tokens": 8,
                 "eos_id": eos, "min_new_tokens": 5}
            )
            s3, bad = fetch(
                {"tokens": [[1, 2, 3]], "max_new_tokens": 4,
                 "min_new_tokens": 9}
            )
            return eos, (s1, stopped), (s2, floored), s3

        out = await loop.run_in_executor(None, go)
        await server.stop()
        return out

    eos, (s1, stopped), (s2, floored), s3 = run(scenario())
    assert s1 == 200 and len(stopped["tokens"][0]) == 2
    assert s2 == 200
    row = floored["tokens"][0]
    assert len(row) >= 5 and eos not in row[:5]
    assert s3 == 422


def test_inference_server_metrics_endpoint(run):
    """GET /metrics: Prometheus exposition with request counts,
    latency histogram, and post-trim token accounting."""
    import json
    import urllib.request

    from containerpilot_tpu.models.transformer import init_params
    from containerpilot_tpu.workload.serve import InferenceServer

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    server = InferenceServer(cfg, params, "127.0.0.1", 0, max_len=32)

    def fetch(path, body=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}{path}",
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"} if body else {},
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.read().decode()

    async def scenario():
        import asyncio

        await server.run()
        loop = asyncio.get_event_loop()

        def go():
            fetch("/v1/generate",
                  {"tokens": [[1, 2, 3]], "max_new_tokens": 6})
            fetch("/v1/generate",
                  {"tokens": [[4, 5]], "max_new_tokens": 4})
            return fetch("/metrics")

        text = await loop.run_in_executor(None, go)
        await server.stop()
        return text

    text = run(scenario())
    assert (
        'containerpilot_serve_requests_total{'
        'code="200",endpoint="generate"} 2.0' in text
    )
    assert "containerpilot_serve_generated_tokens_total 10.0" in text
    assert (
        'containerpilot_serve_request_seconds_count{'
        'endpoint="generate"} 2.0' in text
    )
    # the loopcheck sentinel surfaces on every replica (analysis/
    # loopcheck.py; docs/70 has the runbook for reading it)
    assert 'cp_loop_lag_ms{stat="max"}' in text
    assert 'cp_loop_lag_ms{stat="p99"}' in text


def test_generate_logprobs_echo(run):
    """{"logprobs": true} echoes per-token logprobs of the trimmed
    generated ids via one teacher-forced pass — must match /v1/score
    on prompt+generated at the generated positions (decode == forward
    is the tested invariant that makes this exact)."""
    import json
    import urllib.request

    from containerpilot_tpu.models.transformer import init_params
    from containerpilot_tpu.workload.serve import InferenceServer

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    server = InferenceServer(cfg, params, "127.0.0.1", 0, max_len=32)

    def fetch(path, body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}{path}",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read().decode())

    async def scenario():
        import asyncio

        await server.run()
        loop = asyncio.get_event_loop()

        def go():
            prompt = [1, 2, 3]
            gen = fetch("/v1/generate", {
                "tokens": [prompt], "max_new_tokens": 6,
                "logprobs": True,
            })
            row = gen["tokens"][0]
            score = fetch("/v1/score", {"tokens": [prompt + row]})
            # rows of different trimmed lengths share one echo batch
            eos = row[1]
            two = fetch("/v1/generate", {
                "tokens": [prompt, [4, 5, 6]], "max_new_tokens": 6,
                "eos_id": eos, "logprobs": True,
            })
            return gen, row, score, two

        out = await loop.run_in_executor(None, go)
        await server.stop()
        return out

    gen, row, score, two = run(scenario())
    lps = gen["logprobs"][0]
    assert len(lps) == len(row) and all(x <= 0.0 for x in lps)
    # the echo is exactly the score endpoint's tail slice
    assert lps == score["logprobs"][0][-len(row):]
    for toks, lp_row in zip(two["tokens"], two["logprobs"]):
        assert len(toks) == len(lp_row)


def test_penalties_suppress_repetition(run):
    """presence/frequency penalties subtract from generated-token
    logits across the compiled paths; zero penalties are bitwise
    neutral; out-of-range 422s."""
    import json
    import urllib.error
    import urllib.request

    from containerpilot_tpu.models.transformer import init_params
    from containerpilot_tpu.workload.serve import InferenceServer

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    server = InferenceServer(cfg, params, "127.0.0.1", 0, max_len=32)

    def fetch(body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/generate",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return resp.status, json.loads(resp.read().decode())
        except urllib.error.HTTPError as exc:
            return exc.code, exc.read().decode()

    async def scenario():
        import asyncio

        await server.run()
        loop = asyncio.get_event_loop()

        def go():
            base = {"tokens": [[1, 2, 3]], "max_new_tokens": 8}
            _s, plain = fetch(base)
            _s, zero = fetch({**base, "presence_penalty": 0.0,
                              "frequency_penalty": 0.0})
            s1, norep = fetch({**base, "frequency_penalty": 50.0})
            s2, bad = fetch({**base, "presence_penalty": 1000.0})
            return plain, zero, (s1, norep), s2

        out = await loop.run_in_executor(None, go)
        await server.stop()
        return out

    plain, zero, (s1, norep), s2 = run(scenario())
    assert zero["tokens"] == plain["tokens"]
    row = norep["tokens"][0]
    assert s1 == 200 and len(set(row)) == len(row)
    assert s2 == 422


def test_logit_bias_math_and_validation():
    """apply_logit_bias: -1 slots are bitwise-neutral, entries add
    exactly; normalize_logit_bias rejects the same bounds the HTTP
    layer documents."""
    import numpy as np

    from containerpilot_tpu.models.decode import (
        BIAS_SLOTS,
        BIAS_SLOTS_MAX,
        apply_logit_bias,
        normalize_logit_bias,
    )

    cfg = TransformerConfig(
        vocab_size=32, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=16, dtype=jnp.float32,
    )
    logits = jnp.arange(2 * 32, dtype=jnp.float32).reshape(2, 32)
    idx, val = normalize_logit_bias(
        cfg, 2, [{5: 3.0, 7: -2.0}, None]
    )
    out = apply_logit_bias(logits, jnp.asarray(idx), jnp.asarray(val))
    expect = np.array(logits)  # writable copy
    expect[0, 5] += 3.0
    expect[0, 7] += -2.0
    np.testing.assert_array_equal(np.asarray(out), expect)
    # all-empty bias is bitwise-neutral
    idx0, val0 = normalize_logit_bias(cfg, 2, None)
    np.testing.assert_array_equal(
        np.asarray(
            apply_logit_bias(logits, jnp.asarray(idx0),
                             jnp.asarray(val0))
        ),
        np.asarray(logits),
    )
    for bad in (
        {99: 1.0},             # out of vocab
        {3: 500.0},            # out of range
        {3: 1.0, "x": 1.0},    # unparseable key: ValueError, not
        # a raw TypeError out of sorted() on mixed key types
    ):
        with pytest.raises(ValueError):
            normalize_logit_bias(cfg, 1, bad)
    # str keys are OpenAI's JSON wire form; mixing them with int
    # keys must coerce, not blow up sorting
    idx_m, _val_m = normalize_logit_bias(cfg, 1, {"5": 2.0, 3: 1.0})
    assert sorted(int(i) for i in idx_m[0] if i >= 0) == [3, 5]
    # BIAS_SLOTS is a fast path, not the cap: one entry over it
    # jumps to the wide static table (OpenAI's 300); one entry over
    # THAT is the real 422
    big = TransformerConfig(
        vocab_size=512, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=16, dtype=jnp.float32,
    )
    assert normalize_logit_bias(cfg, 1, {3: 1.0})[0].shape == \
        (1, BIAS_SLOTS)
    idx_w, val_w = normalize_logit_bias(
        big, 1, {i: 1.0 for i in range(BIAS_SLOTS + 1)}
    )
    assert idx_w.shape == (1, BIAS_SLOTS_MAX)
    assert int((idx_w[0] >= 0).sum()) == BIAS_SLOTS + 1
    with pytest.raises(ValueError):
        normalize_logit_bias(
            big, 1, {i: 1.0 for i in range(BIAS_SLOTS_MAX + 1)}
        )


def test_logit_bias_forces_and_bans_across_paths():
    """OpenAI semantics end-to-end: +100 effectively forces a token
    every step, -100 bans one, greedy and sampled — and the slot
    engine's emission matches generate's with the same bias."""
    from containerpilot_tpu.models.decode import generate
    from containerpilot_tpu.models.transformer import init_params
    from containerpilot_tpu.workload.serve_slots import SlotEngine

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)

    forced = generate(
        params, prompt, cfg, 6, 32, logit_bias={9: 100.0}
    )
    assert [int(t) for t in forced[0]] == [9] * 6

    plain = [int(t) for t in generate(params, prompt, cfg, 6, 32)[0]]
    banned_id = plain[0]
    banned = generate(
        params, prompt, cfg, 6, 32, logit_bias={banned_id: -100.0}
    )
    assert banned_id not in [int(t) for t in banned[0]]

    # sampled path: the ban holds under temperature too
    rng = jnp.stack([jax.random.fold_in(jax.random.PRNGKey(7), 0)])
    sampled = generate(
        params, prompt, cfg, 8, 32, temperature=1.2, rng=rng,
        logit_bias={banned_id: -100.0},
    )
    assert banned_id not in [int(t) for t in sampled[0]]

    # slot engine parity with the same bias (server key convention)
    eng = SlotEngine(cfg, params, 32, slots=2, chunk=3)
    try:
        got = eng.submit(
            [1, 2, 3], max_new=6, logit_bias={9: 100.0}
        ).result(timeout=120)
        assert got == [9] * 6
        ref = generate(
            params, prompt, cfg, 6, 32,
            rng=jnp.stack(
                [jax.random.fold_in(jax.random.PRNGKey(0), 0)]
            ),
            logit_bias={banned_id: -100.0},
        )
        got2 = eng.submit(
            [1, 2, 3], max_new=6, logit_bias={banned_id: -100.0}
        ).result(timeout=120)
        assert got2 == [int(t) for t in ref[0]]
        # > BIAS_SLOTS entries ride the wide static table (OpenAI
        # allows 300): 20 banned ids hold on both paths, outputs
        # byte-identical
        wide = {i: -100.0 for i in range(20)}
        ref_w = generate(
            params, prompt, cfg, 6, 32,
            rng=jnp.stack(
                [jax.random.fold_in(jax.random.PRNGKey(0), 0)]
            ),
            logit_bias=wide,
        )
        got_w = eng.submit(
            [1, 2, 3], max_new=6, logit_bias=wide
        ).result(timeout=120)
        assert got_w == [int(t) for t in ref_w[0]]
        assert all(t >= 20 for t in got_w)
    finally:
        eng.stop()


def test_n_samples_over_http(run):
    """OpenAI's n: one prompt, n independent samples as one batched
    device call — row i draws from fold_in(seed, i), so each row
    byte-matches the model-level generate with that key; greedy rows
    are identical by definition; bad compositions 422."""
    import json
    import urllib.error
    import urllib.request

    from containerpilot_tpu.models.decode import generate
    from containerpilot_tpu.models.transformer import init_params
    from containerpilot_tpu.workload.serve import InferenceServer

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    server = InferenceServer(cfg, params, "127.0.0.1", 0, max_len=32)

    def fetch(body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/generate",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=120) as resp:
                return resp.status, json.loads(resp.read().decode())
        except urllib.error.HTTPError as exc:
            return exc.code, exc.read().decode()

    async def scenario():
        import asyncio

        await server.run()
        loop = asyncio.get_event_loop()

        def go():
            base = {"tokens": [[1, 2, 3]], "max_new_tokens": 6}
            s1, sampled = fetch({
                **base, "n": 3, "temperature": 0.9, "seed": 11,
            })
            s2, greedy = fetch({**base, "n": 2})
            s3, _ = fetch({**base, "n": 99})
            s4, _ = fetch({
                "tokens": [[1, 2], [3, 4]], "max_new_tokens": 4,
                "n": 2,
            })
            s5, _ = fetch({**base, "n": 2, "beam_width": 2})
            s6, stream_err = fetch({**base, "n": 2, "stream": True})
            return (s1, sampled), (s2, greedy), s3, s4, s5, \
                (s6, stream_err)

        out = await loop.run_in_executor(None, go)
        await server.stop()
        return out

    ((s1, sampled), (s2, greedy), s3, s4, s5,
     (s6, stream_err)) = run(scenario())
    assert s1 == 200 and len(sampled["tokens"]) == 3
    # row i == model-level generate with the per-row key convention
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    for i, row in enumerate(sampled["tokens"]):
        ref = generate(
            params, prompt, cfg, 6, 32, temperature=0.9,
            rng=jnp.stack(
                [jax.random.fold_in(jax.random.PRNGKey(11), i)]
            ),
        )
        assert row == [int(t) for t in ref[0]], i
    # independent keys actually diversify (not a fixed guarantee in
    # general, but deterministic for this seed/model)
    assert len({tuple(r) for r in sampled["tokens"]}) > 1
    assert s2 == 200 and greedy["tokens"][0] == greedy["tokens"][1]
    assert s3 == s4 == s5 == 422
    # the n+stream 422 names the actual conflict, not the row count
    assert s6 == 422 and "n does not compose with stream" in stream_err


def test_logit_bias_over_http(run):
    """/v1/generate accepts OpenAI's string-keyed logit_bias through
    the batcher path; bad requests 422; beam rejects it."""
    import json
    import urllib.error
    import urllib.request

    from containerpilot_tpu.models.transformer import init_params
    from containerpilot_tpu.workload.serve import InferenceServer

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    server = InferenceServer(cfg, params, "127.0.0.1", 0, max_len=32)

    def fetch(body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/generate",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return resp.status, json.loads(resp.read().decode())
        except urllib.error.HTTPError as exc:
            return exc.code, exc.read().decode()

    async def scenario():
        import asyncio

        await server.run()
        loop = asyncio.get_event_loop()

        def go():
            base = {"tokens": [[1, 2, 3]], "max_new_tokens": 5}
            s_plain, plain = fetch(base)
            s_force, forced = fetch(
                {**base, "logit_bias": {"9": 100}}
            )
            # OpenAI semantics: an empty map is a no-op, not an error
            s_empty, empty = fetch({**base, "logit_bias": {}})
            s_bad1, _ = fetch({**base, "logit_bias": {"999": 1}})
            s_bad2, _ = fetch({**base, "logit_bias": {"3": 1000}})
            s_bad3, _ = fetch({**base, "logit_bias": []})
            s_beam, beam_err = fetch(
                {**base, "logit_bias": {"9": 1}, "beam_width": 2}
            )
            return (s_plain, plain), (s_force, forced), \
                (s_empty, empty), s_bad1, s_bad2, s_bad3, \
                (s_beam, beam_err)

        out = await loop.run_in_executor(None, go)
        await server.stop()
        return out

    ((s_plain, plain), (s_force, forced), (s_empty, empty), s_bad1,
     s_bad2, s_bad3, (s_beam, beam_err)) = run(scenario())
    assert s_force == 200 and forced["tokens"][0] == [9] * 5
    assert s_plain == s_empty == 200
    assert empty["tokens"] == plain["tokens"]
    assert s_bad1 == s_bad2 == s_bad3 == 422
    assert s_beam == 422 and "beam" in beam_err


def test_fuzz_generate_knob_combinations():
    """Random combinations of every sampling knob against the
    invariants that must hold regardless: output shape, pads after
    eos, min_new eos suppression, seed determinism, and in-vocab ids
    (penalty EFFECTS are asserted by their dedicated tests; here the
    knobs only widen the combination space). Knob values are drawn so
    the combos reuse a small
    set of compiled programs (max_new fixed; greedy/filtered/
    penalized/biased each toggled)."""
    import random

    import jax
    import jax.numpy as jnp
    import numpy as np

    from containerpilot_tpu.models.decode import generate
    from containerpilot_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = random.Random(7)
    max_new = 8

    for trial in range(12):
        greedy = rng.random() < 0.4
        kw = {
            "temperature": 0.0 if greedy else rng.uniform(0.3, 1.5),
            "top_k": rng.choice([0, 0, 5, 40]),
            "top_p": rng.choice([0.0, 0.0, 0.7, 0.95]),
            "eos_id": rng.choice([-1, rng.randrange(cfg.vocab_size)]),
            "min_new_tokens": rng.choice([0, 0, 3]),
            "presence_penalty": rng.choice([0.0, 0.0, 1.5]),
            "frequency_penalty": rng.choice([0.0, 0.0, 2.0]),
            "logit_bias": rng.choice([
                None, None,
                {rng.randrange(cfg.vocab_size): rng.choice([-100.0, -5.0, 5.0])},
            ]),
        }
        prompt = jnp.asarray(
            [[rng.randrange(cfg.vocab_size) for _ in range(4)]],
            jnp.int32,
        )
        key = jax.random.PRNGKey(trial)
        out1 = np.asarray(generate(
            params, prompt, cfg, max_new, 32, rng=key, **kw
        ))[0]
        out2 = np.asarray(generate(
            params, prompt, cfg, max_new, 32, rng=key, **kw
        ))[0]
        label = f"trial {trial}: {kw}"
        assert out1.shape == (max_new,), label
        assert (out1 == out2).all(), f"nondeterministic: {label}"
        assert ((out1 >= 0) & (out1 < cfg.vocab_size)).all(), label
        eos = kw["eos_id"]
        if eos >= 0:
            hits = np.flatnonzero(out1 == eos)
            if hits.size:
                first = int(hits[0])
                # eos never before the floor...
                assert first >= kw["min_new_tokens"], label
                # ...and everything after the first eos is pad (0)
                assert (out1[first + 1:] == 0).all(), label
        bias = kw["logit_bias"]
        if bias:
            ((tok, val),) = bias.items()
            if val <= -100.0 and tok != 0 and tok != eos:
                # a full ban keeps the token out (pad 0 and eos fill
                # rows for other reasons, so those ids are exempt)
                assert tok not in out1, label
