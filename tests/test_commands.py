"""Command execution tests (reference: commands/commands_test.go,
args_test.go — behavior parity, not translation)."""
import asyncio
import os
import signal

import pytest

from containerpilot_tpu.commands import ArgsError, Command, parse_args
from containerpilot_tpu.events import Event, EventBus, EventCode


def test_parse_args_string_and_list():
    assert parse_args("/bin/echo hi there") == ("/bin/echo", ["hi", "there"])
    assert parse_args(["/bin/echo", "one two"]) == ("/bin/echo", ["one two"])
    assert parse_args("lone") == ("lone", [])
    for bad in ("", [], None, 42):
        with pytest.raises(ArgsError):
            parse_args(bad)


def test_env_name():
    assert Command("/bin/to-db.sh", name="/bin/to-db.sh").env_name() == "TO_DB"
    assert Command("x", name="my job.1").env_name() == "MY_JOB"
    assert Command("x", name="app").env_name() == "APP"


def test_run_success_publishes_exit_success(run):
    async def scenario():
        bus = EventBus()
        cmd = Command.from_config("true", name="ok")
        rc = await cmd.run(bus)
        return rc, bus.debug_events()

    rc, ring = run(scenario())
    assert rc == 0
    assert ring == [Event(EventCode.EXIT_SUCCESS, "ok")]


def test_run_failure_publishes_exit_failed_and_error(run):
    async def scenario():
        bus = EventBus()
        cmd = Command.from_config("false", name="bad")
        rc = await cmd.run(bus)
        return rc, bus.debug_events()

    rc, ring = run(scenario())
    assert rc == 1
    assert ring[0] == Event(EventCode.EXIT_FAILED, "bad")
    assert ring[1].code == EventCode.ERROR


def test_spawn_failure_publishes_events(run):
    async def scenario():
        bus = EventBus()
        cmd = Command.from_config("/no/such/binary", name="ghost")
        rc = await cmd.run(bus)
        return rc, bus.debug_events()

    rc, ring = run(scenario())
    assert rc is None
    assert ring[0] == Event(EventCode.EXIT_FAILED, "ghost")
    assert ring[1].code == EventCode.ERROR


def test_timeout_kills_process_group(run):
    async def scenario():
        bus = EventBus()
        cmd = Command.from_config("sleep 10", timeout=0.1, name="sleepy")
        rc = await cmd.run(bus)
        return rc, bus.debug_events()

    rc, ring = run(scenario(), timeout=5)
    assert rc == -signal.SIGKILL
    assert ring[0] == Event(EventCode.EXIT_FAILED, "sleepy")


def test_term_signals_group(run):
    async def scenario():
        bus = EventBus()
        cmd = Command.from_config("sleep 10", name="victim")
        task = cmd.run(bus)
        for _ in range(100):
            await asyncio.sleep(0.01)
            if cmd.running:
                break
        cmd.term()
        rc = await task
        return rc, bus.debug_events()

    rc, ring = run(scenario(), timeout=5)
    assert rc == -signal.SIGTERM
    assert ring[0] == Event(EventCode.EXIT_FAILED, "victim")


def test_pid_env_exported_during_run(run):
    async def scenario():
        bus = EventBus()
        cmd = Command.from_config(
            ["/bin/sh", "-c", 'echo "pid=$CONTAINERPILOT_PROBE_PID"'],
            fields={"job": "probe"},
            name="probe",
        )
        rc = await cmd.run(bus)
        # env cleaned up after exit
        return rc, os.environ.get("CONTAINERPILOT_PROBE_PID")

    rc, leftover = run(scenario())
    assert rc == 0
    assert leftover is None


def test_captured_logging_vs_raw(run, caplog):
    async def scenario():
        bus = EventBus()
        cmd = Command.from_config(
            "echo hello-from-child", fields={"job": "echoer"}, name="echoer"
        )
        await cmd.run(bus)

    import logging

    with caplog.at_level(logging.INFO, logger="containerpilot.job.echoer"):
        run(scenario())
    assert any("hello-from-child" in r.message for r in caplog.records)
