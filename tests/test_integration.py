"""Integration scenarios mirroring the reference's docker-compose tests
(reference: integration_tests/tests/*; SURVEY.md §4.2): real CLI, real
processes, assertions on observable state."""
import asyncio
import os
import signal
import subprocess
import sys
import time

import pytest

from containerpilot_tpu.client import ControlClient
from containerpilot_tpu.core import App

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CPSUP = os.path.join(REPO, "native", "cpsup")


def write_config(tmp_path, text):
    path = tmp_path / "containerpilot.json5"
    path.write_text(text)
    return str(path)


def test_coprocess_restart_budget_resets_on_reload(run, tmp_path):
    """integration test_coprocess: kill coprocess -> restarts once
    (restarts: 1); kill again -> stays dead; reload -> budget reset."""
    socket_path = str(tmp_path / "cp.socket")
    pidfile = tmp_path / "co.pid"
    config = """
    {
      stopTimeout: "1ms",
      control: { socket: "%s" },
      jobs: [
        { name: "anchor", exec: "sleep 60" },
        {
          name: "coprocess",
          exec: ["/bin/sh", "-c", "echo $$ > %s; exec sleep 60"],
          restarts: 1,
        },
      ],
    }
    """ % (socket_path, pidfile)
    path = write_config(tmp_path, config)

    def read_pid():
        return int(pidfile.read_text())

    async def kill_co_and_wait(old_pid):
        os.kill(old_pid, signal.SIGKILL)
        for _ in range(100):
            await asyncio.sleep(0.05)
            if pidfile.exists():
                try:
                    new = read_pid()
                except ValueError:
                    continue
                if new != old_pid:
                    return new
        return old_pid

    async def scenario():
        app = App.from_config_path(path)
        run_task = asyncio.get_event_loop().create_task(app.run())
        await asyncio.sleep(0.4)
        pid1 = read_pid()
        pid2 = await kill_co_and_wait(pid1)        # budget 1 -> restarts
        assert pid2 != pid1, "first kill should restart the coprocess"
        os.kill(pid2, signal.SIGKILL)              # budget exhausted
        await asyncio.sleep(0.6)
        pid3 = read_pid()
        assert pid3 == pid2, "second kill must NOT restart"
        # reload resets the restart budget
        client = ControlClient(socket_path)
        await asyncio.get_event_loop().run_in_executor(None, client.reload)
        for _ in range(100):
            await asyncio.sleep(0.05)
            try:
                if read_pid() not in (pid2, pid1):
                    break
            except ValueError:
                pass
        pid4 = read_pid()
        assert pid4 not in (pid1, pid2), "reload must start a fresh coprocess"
        pid5 = await kill_co_and_wait(pid4)        # fresh budget -> restart
        assert pid5 != pid4, "restart budget must be reset after reload"
        app.terminate()
        await asyncio.wait_for(run_task, timeout=20)
        return True

    assert run(scenario(), timeout=60)


def _proc_state_ppid(pid):
    """(state, ppid) from /proc/<pid>/stat, or None if the process is
    gone. Split after the last ')' — comm may contain spaces."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            rest = f.read().rsplit(")", 1)[1].split()
        return rest[0], int(rest[1])
    except (OSError, IndexError, ValueError):
        return None


def _drive_orphan_reaper(spawn, tmp_path):
    """Shared honest-reaping scenario (reference:
    integration_tests/tests/test_reap_zombies/run.sh:24-30): the
    worker double-forks an orphan that lingers, so we can assert it
    actually REPARENTED onto the init process (subreaper) — the old
    vacuous test counted zombies whose parent was cpsup, of which
    there were zero by construction because orphans went to the real
    init — and then that the init's waitpid(-1) loop collected it."""
    pidfile = tmp_path / "orphan.pid"
    # ( cmd & ) double-forks: the subshell parent exits at once; the
    # orphan sleeps until WE kill it, so no assertion races a fixed
    # lifetime on a loaded single-core box
    # exec keeps the orphan a single process; >/dev/null detaches it
    # from the worker's stdio pipes so nothing outlives it holding them
    script = (
        f"( sh -c 'echo $$ > {pidfile}; exec sleep 120' "
        "> /dev/null 2>&1 & ) ; sleep 120"
    )
    proc = spawn(script)
    orphan = None
    try:
        deadline = time.monotonic() + 10
        while True:
            assert time.monotonic() < deadline, "orphan never spawned"
            try:
                orphan = int(pidfile.read_text())
                break
            except (OSError, ValueError):
                time.sleep(0.02)
        # 1) the orphan must reparent onto the init-under-test while
        # it is still alive (subreaper status; fails on a cpsup
        # without PR_SET_CHILD_SUBREAPER: PPID lands on the real init)
        deadline = time.monotonic() + 10
        last = None
        while True:
            last = _proc_state_ppid(orphan)
            if last is not None and last[1] == proc.pid:
                break
            assert time.monotonic() < deadline, (
                f"orphan {orphan} never reparented onto the "
                f"supervisor {proc.pid} (last stat {last}; "
                "PR_SET_CHILD_SUBREAPER missing?)"
            )
            time.sleep(0.02)
        # 2) kill it: it must be REAPED, not left a zombie child
        os.kill(orphan, signal.SIGKILL)
        deadline = time.monotonic() + 10
        while _proc_state_ppid(orphan) is not None:
            state, _ = _proc_state_ppid(orphan) or ("", 0)
            assert time.monotonic() < deadline, (
                f"orphan {orphan} still present (state {state!r}) — "
                "the waitpid(-1) loop never collected it"
            )
            time.sleep(0.05)
    finally:
        proc.terminate()
        proc.wait(timeout=10)


@pytest.mark.skipif(not os.path.exists(CPSUP), reason="cpsup not built")
def test_cpsup_reaps_zombies(tmp_path):
    """integration test_reap_zombies: orphans reparent onto cpsup
    (child-subreaper) and its waitpid(-1) loop collects them."""
    def spawn(script):
        return subprocess.Popen(
            [CPSUP, "/bin/sh", "-c", script],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )

    _drive_orphan_reaper(spawn, tmp_path)


def test_sup_py_reaps_zombies(tmp_path):
    """The Python sup fallback claims subreaper status too (ctypes
    prctl) and reaps orphans exactly like the native binary."""
    code = (
        "import sys; from containerpilot_tpu.sup import run_sup; "
        "sys.exit(run_sup(['containerpilot', '-config', sys.argv[1]]))"
    )

    def spawn(script):
        cfg = write_config(
            tmp_path,
            """
            {
              stopTimeout: "1ms",
              jobs: [ { name: "main", exec: ["/bin/sh", "-c", %s] } ],
            }
            """ % repr(script),
        )
        return subprocess.Popen(
            [sys.executable, "-c", code, cfg], cwd=REPO,
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
        )

    _drive_orphan_reaper(spawn, tmp_path)


def _unshare_available():
    try:
        return subprocess.run(
            ["unshare", "--pid", "--fork", "--mount-proc", "true"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            timeout=15,
        ).returncode == 0
    except (OSError, subprocess.TimeoutExpired):
        return False


@pytest.mark.skipif(not os.path.exists(CPSUP), reason="cpsup not built")
@pytest.mark.skipif(
    not _unshare_available(), reason="unshare --pid not permitted"
)
def test_container_entrypoint_arrangement_ns_pid1(tmp_path):
    """The Dockerfile's ENTRYPOINT arrangement — cpsup as literal
    PID 1 running `python -m containerpilot_tpu -config ...` — driven
    in a PID namespace (`unshare --pid --fork --mount-proc`), docker
    not required (reference: integration_tests/tests/
    test_reap_zombies/run.sh:14-36 runs the same shape in-container).

    Asserts, from inside the namespace: the orphan reparents to PID 1
    (cpsup), and after it exits no zombie remains in the ns /proc;
    from outside: all jobs complete -> supervisor exit 0 propagates
    through cpsup and unshare."""
    report = tmp_path / "report.txt"
    probe = tmp_path / "probe.sh"
    probe.write_text(
        """#!/bin/sh
# runs as the supervisor's job INSIDE the pid ns (mount-proc'd).
# Poll, never fixed-sleep: the box has one core and fixed lifetimes
# race under load. The orphan sleeps until we kill it.
# exec -> the orphan is one process; /dev/null -> it does not hold
# the job's stdout pipe open past the probe (the supervisor waits on
# pipe EOF after the job exits)
( sh -c 'echo $$ > {tmp}/orphan.pid; exec sleep 120' \
  > /dev/null 2>&1 & )
i=0
while [ ! -s {tmp}/orphan.pid ] && [ $i -lt 200 ]; do
  i=$((i + 1)); sleep 0.05
done
read OP < {tmp}/orphan.pid
# after the intermediate subshell exits the orphan's parent must
# become the namespace's PID 1 = cpsup
i=0; P=unset
while [ $i -lt 200 ]; do
  P=$(awk '{{print $4}}' /proc/$OP/stat 2>/dev/null || echo gone)
  [ "$P" = 1 ] && break
  i=$((i + 1)); sleep 0.05
done
echo "orphan_ppid=$P" >> {report}
# kill it: PID 1's waitpid(-1) loop must collect the zombie
kill -9 $OP
i=0
while [ -e /proc/$OP ] && [ $i -lt 200 ]; do
  i=$((i + 1)); sleep 0.05
done
if [ -e /proc/$OP ]; then R=no; else R=yes; fi
echo "reaped=$R" >> {report}
echo "init_comm=$(awk '{{print $2}}' /proc/1/stat)" >> {report}
""".format(tmp=tmp_path, report=report)
    )
    probe.chmod(0o755)
    cfg = write_config(
        tmp_path,
        """
        { stopTimeout: "1ms",
          jobs: [ { name: "probe", exec: "%s" } ] }
        """ % probe,
    )
    proc = subprocess.run(
        ["unshare", "--pid", "--fork", "--mount-proc",
         CPSUP, sys.executable, "-m", "containerpilot_tpu",
         "-config", cfg],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout.decode()
    got = dict(
        line.split("=", 1)
        for line in report.read_text().splitlines() if "=" in line
    )
    assert got["orphan_ppid"] == "1", got   # reparented onto cpsup
    assert got["reaped"] == "yes", got      # and actually collected
    assert got["init_comm"] == "(cpsup)", got


@pytest.mark.skipif(not os.path.exists(CPSUP), reason="cpsup not built")
def test_cpsup_forwards_term_and_propagates_exit():
    proc = subprocess.Popen(
        [CPSUP, "/bin/sh", "-c", "trap 'exit 9' TERM; sleep 30 & wait"],
        stdout=subprocess.PIPE,
    )
    time.sleep(0.5)
    proc.send_signal(signal.SIGTERM)
    assert proc.wait(timeout=10) == 9


def test_sigusr1_reopens_log_file(run, tmp_path):
    """integration test_reopen: after the log file is rotated away,
    SIGUSR1 makes the supervisor reopen it at the configured path."""
    log_path = tmp_path / "cp.log"
    rotated = tmp_path / "cp.log.1"
    path = write_config(
        tmp_path,
        """
        {
          stopTimeout: "1ms",
          logging: { level: "INFO", output: "%s" },
          jobs: [
            {
              name: "chatty",
              exec: ["/bin/sh", "-c", "echo hello"],
              when: { interval: "200ms" },
            },
          ],
        }
        """
        % log_path,
    )

    async def scenario():
        app = App.from_config_path(path)
        run_task = asyncio.get_event_loop().create_task(app.run())
        await asyncio.sleep(0.6)
        os.rename(log_path, rotated)  # logrotate
        from containerpilot_tpu.config.logger import reopen_log_file

        reopen_log_file()  # what the SIGUSR1 handler calls
        await asyncio.sleep(0.8)
        app.terminate()
        await asyncio.wait_for(run_task, timeout=20)
        return log_path.exists() and log_path.stat().st_size > 0

    assert run(scenario(), timeout=30)


def test_version_flag_cli():
    """integration test_version_flag."""
    out = subprocess.run(
        [sys.executable, "-m", "containerpilot_tpu", "-version"],
        capture_output=True, text=True, cwd=REPO, timeout=60,
    )
    assert out.returncode == 0
    assert "Version:" in out.stdout


def test_no_command_is_error():
    """integration test_no_command: missing config is a clean error."""
    out = subprocess.run(
        [sys.executable, "-m", "containerpilot_tpu"],
        capture_output=True, text=True, cwd=REPO, timeout=60,
        env={**os.environ, "CONTAINERPILOT": ""},
    )
    assert out.returncode == 1
    assert "-config flag is required" in out.stderr


def test_real_sigterm_through_cli(tmp_path):
    """Spawn the actual CLI, deliver a real SIGTERM, assert the pre-stop
    hook ran and the exit was clean (integration test_sigterm)."""
    order = tmp_path / "order.log"
    started = tmp_path / "started"
    sup_log = tmp_path / "supervisor.log"
    path = write_config(
        tmp_path,
        """
        {
          stopTimeout: "1ms",
          jobs: [
            {
              name: "main",
              exec: ["/bin/sh", "-c", "echo $$ > %s; exec sleep 60"],
              stopTimeout: "5s",
            },
            {
              name: "preStop",
              exec: ["/bin/sh", "-c", "echo PRESTOP >> %s"],
              when: { once: "stopping", source: "main" },
            },
          ],
        }
        """
        % (started, order),
    )
    with open(sup_log, "wb") as log_f:
        proc = subprocess.Popen(
            [sys.executable, "-m", "containerpilot_tpu", "-config", path],
            cwd=REPO, stdout=log_f, stderr=subprocess.STDOUT,
        )
    try:
        # poll for readiness instead of racing startup with a sleep
        deadline = time.monotonic() + 30
        while not started.exists():
            assert time.monotonic() < deadline, (
                f"main never started; log:\n{sup_log.read_text()}"
            )
            time.sleep(0.05)
        time.sleep(0.3)  # signal handlers installed before jobs run
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=30)
        assert rc == 0, f"exit {rc}; log:\n{sup_log.read_text()}"
        assert "PRESTOP" in order.read_text()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        # on failure paths the job child may outlive the supervisor;
        # its pid was written to the sentinel file
        try:
            os.kill(int(started.read_text()), signal.SIGKILL)
        except (OSError, ValueError):
            pass


def test_template_render_to_file(tmp_path):
    """-template -out writes the rendered config (render subcommand)."""
    cfg = tmp_path / "t.json5"
    out = tmp_path / "rendered.json5"
    cfg.write_text(
        '{ jobs: [{ name: "app",'
        ' exec: "run {{ .CP_TEST_UNSET_93 | default "1" }}" }] }'
    )
    env = {k: v for k, v in os.environ.items() if k != "CP_TEST_UNSET_93"}
    result = subprocess.run(
        [sys.executable, "-m", "containerpilot_tpu", "-template",
         "-config", str(cfg), "-out", str(out)],
        cwd=REPO, capture_output=True, text=True, timeout=60, env=env,
    )
    assert result.returncode == 0, result.stderr
    assert 'exec: "run 1"' in out.read_text()


def test_python_sup_fallback_propagates_exit_code():
    """The pure-Python PID-1 fallback: forks the worker and propagates
    its exit code."""
    code = (
        "import sys; from containerpilot_tpu.sup import run_sup; "
        "sys.exit(run_sup(['containerpilot', '-version']))"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        cwd=REPO, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    assert "Version:" in out.stdout


def test_python_sup_fallback_forwards_sigterm(tmp_path):
    """SIGTERM to the sup process forwards to the worker supervisor,
    which shuts down gracefully (pre-stop hook runs, exit 0)."""
    order = tmp_path / "order.log"
    started = tmp_path / "started"
    cfg = write_config(
        tmp_path,
        """
        {
          stopTimeout: "1ms",
          jobs: [
            { name: "main",
              exec: ["/bin/sh", "-c", "touch %s; exec sleep 60"],
              stopTimeout: "5s" },
            { name: "preStop",
              exec: ["/bin/sh", "-c", "echo PRESTOP >> %s"],
              when: { once: "stopping", source: "main" } },
          ],
        }
        """
        % (started, order),
    )
    code = (
        "import sys; from containerpilot_tpu.sup import run_sup; "
        f"sys.exit(run_sup(['containerpilot', '-config', {str(cfg)!r}]))"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", code], cwd=REPO,
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
    )
    try:
        deadline = time.monotonic() + 30
        while not started.exists():
            assert time.monotonic() < deadline, "worker never started"
            time.sleep(0.05)
        time.sleep(0.3)
        proc.send_signal(signal.SIGTERM)  # to sup, NOT the worker
        rc = proc.wait(timeout=30)
        assert rc == 0
        assert "PRESTOP" in order.read_text()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)


def _spawn_cli(config_path, log_path, env=None):
    log_f = open(log_path, "wb")
    proc = subprocess.Popen(
        [sys.executable, "-m", "containerpilot_tpu", "-config", config_path],
        cwd=REPO, stdout=log_f, stderr=subprocess.STDOUT,
        env=dict(os.environ, **(env or {})),
    )
    proc._log_f = log_f  # keep the handle alive with the process
    return proc


def _teardown_cli(proc, timeout=30):
    if proc.poll() is None:
        proc.terminate()
        try:
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)
    proc._log_f.close()


def _free_port():
    import socket as socketlib

    with socketlib.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_for(path, deadline_s=30, what="sentinel"):
    deadline = time.monotonic() + deadline_s
    while not os.path.exists(str(path)):
        assert time.monotonic() < deadline, f"{what} never appeared"
        time.sleep(0.05)


def test_real_sighup_triggers_signal_job(tmp_path):
    """A REAL SIGHUP delivered to the running CLI runs when.source:
    SIGHUP jobs and does NOT reload/exit (v3 semantics; reference:
    integration_tests/tests/test_sighup, core/signals.go:24-27)."""
    started = tmp_path / "started"
    hupped = tmp_path / "hupped"
    cfg = write_config(
        tmp_path,
        """
        {
          stopTimeout: "1ms",
          jobs: [
            { name: "main",
              exec: ["/bin/sh", "-c", "touch %s; exec sleep 60"] },
            { name: "on-hup",
              exec: ["/bin/sh", "-c", "echo HUP >> %s"],
              when: { source: "SIGHUP" } },
          ],
        }
        """
        % (started, hupped),
    )
    proc = _spawn_cli(cfg, tmp_path / "sup.log")
    try:
        _wait_for(started, what="main job")
        time.sleep(0.3)  # handlers installed before jobs run
        proc.send_signal(signal.SIGHUP)
        _wait_for(hupped, what="SIGHUP-triggered job")
        # SIGHUP is an event, not a reload: the supervisor stays up
        time.sleep(0.3)
        assert proc.poll() is None
        # a second SIGHUP runs it again ("each" semantics by default)
        proc.send_signal(signal.SIGHUP)
        deadline = time.monotonic() + 30
        while hupped.read_text().count("HUP") < 2:
            assert time.monotonic() < deadline, "second SIGHUP never ran"
            time.sleep(0.05)
    finally:
        _teardown_cli(proc)


def test_putenv_visible_to_next_generation_exec(tmp_path):
    """-putenv persists an env var across reload and the NEXT
    generation's rendered exec sees it (reference:
    integration_tests/tests/test_envvars + control/endpoints.go:57-72)."""
    socket_path = str(tmp_path / "cp.socket")
    out = tmp_path / "rendered"
    started = tmp_path / "started"
    cfg = write_config(
        tmp_path,
        """
        {
          stopTimeout: "1ms",
          control: { socket: "%s" },
          jobs: [
            { name: "main",
              exec: ["/bin/sh", "-c", "touch %s; exec sleep 60"],
              restarts: "unlimited" },
            { name: "render-env",
              exec: ["/bin/sh", "-c",
                     "echo RENDERED={{ .ROUND2_FLAG | default "unset" }} >> %s"] },
          ],
        }
        """
        % (socket_path, started, out),
    )
    proc = _spawn_cli(cfg, tmp_path / "sup.log")
    try:
        _wait_for(started, what="first generation")
        _wait_for(out, what="first render")
        assert "RENDERED=unset" in out.read_text()

        rc = subprocess.run(
            [sys.executable, "-m", "containerpilot_tpu",
             "-putenv", "ROUND2_FLAG=set-via-control",
             "-config", cfg],
            cwd=REPO, capture_output=True, text=True, timeout=30,
        )
        assert rc.returncode == 0, rc.stderr
        rc = subprocess.run(
            [sys.executable, "-m", "containerpilot_tpu",
             "-reload", "-config", cfg],
            cwd=REPO, capture_output=True, text=True, timeout=30,
        )
        assert rc.returncode == 0, rc.stderr

        # the reloaded generation re-renders the template against the
        # updated supervisor environment
        deadline = time.monotonic() + 30
        while "RENDERED=set-via-control" not in out.read_text():
            assert time.monotonic() < deadline, (
                f"next generation never saw putenv: {out.read_text()!r}"
            )
            time.sleep(0.1)
    finally:
        _teardown_cli(proc)


def test_two_supervisors_discover_via_catalog(tmp_path):
    """Two real supervisors + a live catalog server: A advertises a
    health-checked service, B's watch observes it appear and fires the
    dependent job (reference:
    integration_tests/tests/test_discovery_consul)."""
    catalog_port = _free_port()
    svc_port = _free_port()
    seen = tmp_path / "seen"
    a_started = tmp_path / "a_started"

    catalog = subprocess.Popen(
        [sys.executable, "-m", "containerpilot_tpu",
         "-catalog-server", f"127.0.0.1:{catalog_port}"],
        cwd=REPO, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    proc_a = proc_b = None
    try:
        import urllib.request

        deadline = time.monotonic() + 30
        while True:
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{catalog_port}/v1/health/service/x",
                    timeout=1,
                )
                break
            except Exception:
                assert time.monotonic() < deadline, "catalog never came up"
                time.sleep(0.2)

        cfg_a = tmp_path / "a.json5"
        cfg_a.write_text(
            """
            {
              consul: "127.0.0.1:%d",
              stopTimeout: "1ms",
              jobs: [
                { name: "svc-a",
                  exec: ["/bin/sh", "-c", "touch %s; exec sleep 60"],
                  port: %d,
                  interfaces: ["static:127.0.0.1"],
                  health: { exec: "/bin/true", interval: 1, ttl: 5 } },
              ],
            }
            """
            % (catalog_port, a_started, svc_port)
        )
        cfg_b = tmp_path / "b.json5"
        cfg_b.write_text(
            """
            {
              consul: "127.0.0.1:%d",
              stopTimeout: "1ms",
              jobs: [
                { name: "observer",
                  exec: ["/bin/sh", "-c", "echo CHANGED >> %s"],
                  when: { each: "changed", source: "watch.svc-a" } },
                { name: "keepalive", exec: "sleep 60" },
              ],
              watches: [ { name: "svc-a", interval: 1 } ],
            }
            """
            % (catalog_port, seen)
        )
        proc_b = _spawn_cli(str(cfg_b), tmp_path / "b.log")
        time.sleep(0.5)
        proc_a = _spawn_cli(str(cfg_a), tmp_path / "a.log")
        _wait_for(a_started, what="supervisor A's service")
        # B's watch poll sees svc-a appear in the catalog -> observer runs
        _wait_for(seen, deadline_s=60, what="B observing A via catalog")
        assert "CHANGED" in seen.read_text()
    finally:
        for p in (proc_a, proc_b):
            if p is not None:
                _teardown_cli(p)
        catalog.terminate()
        catalog.wait(timeout=10)


def test_catalog_server_snapshot_survives_restart(tmp_path):
    """cp-catalogd with -catalog-snapshot: SIGTERM the daemon, restart
    it, and the registrations it held are served again immediately —
    the supervised-catalog self-heal story (a catalog restart no longer
    blanks the pod's view until every host re-heartbeats)."""
    import json as json_mod
    import urllib.request

    port = _free_port()
    snap = tmp_path / "catalog.json"

    def spawn():
        return subprocess.Popen(
            [sys.executable, "-m", "containerpilot_tpu",
             "-catalog-server", f"127.0.0.1:{port}",
             "-catalog-snapshot", str(snap)],
            cwd=REPO, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )

    def wait_up():
        deadline = time.monotonic() + 30
        while True:
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/v1/health/service/x",
                    timeout=1,
                )
                return
            except Exception:
                assert time.monotonic() < deadline, "catalog never came up"
                time.sleep(0.2)

    def health(name):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/v1/health/service/{name}?passing=1",
            timeout=5,
        ) as resp:
            return json_mod.loads(resp.read().decode())

    catalog = spawn()
    try:
        wait_up()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/agent/service/register",
            method="PUT",
            data=json_mod.dumps(
                {"ID": "svc-h1", "Name": "svc", "Address": "10.0.0.4",
                 "Port": 9000,
                 "Check": {"TTL": "30s", "Status": "passing"}}
            ).encode(),
        )
        urllib.request.urlopen(req, timeout=5)
        assert len(health("svc")) == 1
        catalog.terminate()  # stop() writes the final snapshot
        assert catalog.wait(timeout=10) == 0
        assert snap.exists()

        catalog = spawn()
        wait_up()
        entries = health("svc")
        assert len(entries) == 1, f"restart lost the catalog: {entries}"
        assert entries[0]["Service"]["Address"] == "10.0.0.4"
    finally:
        catalog.terminate()
        catalog.wait(timeout=10)


def test_periodic_task_through_cli(tmp_path):
    """An interval job ticks repeatedly in the real supervisor
    (reference: integration_tests/tests/test_tasks)."""
    ticks = tmp_path / "ticks"
    started = tmp_path / "started"
    cfg = write_config(
        tmp_path,
        """
        {
          stopTimeout: "1ms",
          jobs: [
            { name: "main",
              exec: ["/bin/sh", "-c", "touch %s; exec sleep 60"] },
            { name: "tick",
              exec: ["/bin/sh", "-c", "echo T >> %s"],
              when: { interval: "200ms" } },
          ],
        }
        """
        % (started, ticks),
    )
    proc = _spawn_cli(cfg, tmp_path / "sup.log")
    try:
        _wait_for(started, what="main job")
        deadline = time.monotonic() + 30
        while not (ticks.exists() and ticks.read_text().count("T") >= 3):
            assert time.monotonic() < deadline, "periodic task never ticked"
            time.sleep(0.05)
        proc.terminate()
        assert proc.wait(timeout=30) == 0
    finally:
        _teardown_cli(proc)


def test_telemetry_metrics_e2e(tmp_path):
    """Reference integration test_telemetry: a sensor job reports a
    custom metric through `-putmetric`, and /metrics (real HTTP, real
    CLI) exposes it alongside the built-in supervisor metrics;
    /status reports the jobs (reference:
    integration_tests/tests/test_telemetry/check.sh)."""
    import json as jsonlib
    import urllib.request

    port = _free_port()
    socket_path = str(tmp_path / "cp.socket")
    started = tmp_path / "started"
    cfg = write_config(
        tmp_path,
        """
        {
          consul: "file:%s",
          stopTimeout: "1ms",
          control: { socket: "%s" },
          telemetry: {
            port: %d,
            interfaces: ["static:127.0.0.1"],
            metrics: [
              { name: "sensor_reading", help: "fake sensor",
                type: "gauge" },
            ],
          },
          jobs: [
            { name: "main",
              exec: ["/bin/sh", "-c", "touch %s; exec sleep 60"] },
            { name: "sensor",
              exec: ["%s", "-m", "containerpilot_tpu",
                     "-putmetric", "sensor_reading=42.5",
                     "-config", "{{ .CP_CONFIG }}"] },
          ],
        }
        """
        % (tmp_path / "catalog", socket_path, port, started,
           sys.executable),
    )
    proc = _spawn_cli(cfg, tmp_path / "sup.log",
                      env={"CP_CONFIG": cfg})
    try:
        _wait_for(started, what="main job")

        def fetch(path):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5
            ) as resp:
                return resp.read().decode()

        deadline = time.monotonic() + 30
        while True:
            try:
                body = fetch("/metrics")
                if "sensor_reading 42.5" in body:
                    break
            except OSError:
                pass
            assert time.monotonic() < deadline, (
                "sensor metric never appeared on /metrics"
            )
            time.sleep(0.2)
        # built-in supervisor metrics ride the same exposition
        assert "containerpilot_events" in body
        status = jsonlib.loads(fetch("/status"))
        names = {j["Name"] for j in status["Jobs"]}
        assert "main" in names and "sensor" in names
    finally:
        _teardown_cli(proc)


def test_logging_json_format_e2e(tmp_path):
    """Reference integration test_logging: the supervisor logs in the
    configured format — every line of json-format output parses as a
    JSON object with time/level/msg (reference:
    integration_tests/tests/test_logging + config/logger)."""
    import json as jsonlib

    log_file = tmp_path / "cp.json.log"
    started = tmp_path / "started"
    cfg = write_config(
        tmp_path,
        """
        {
          stopTimeout: "1ms",
          logging: { level: "DEBUG", format: "json", output: "%s" },
          jobs: [
            { name: "main",
              exec: ["/bin/sh", "-c", "touch %s; exit 0"] },
          ],
        }
        """
        % (log_file, started),
    )
    proc = _spawn_cli(cfg, tmp_path / "stdout.log")
    try:
        _wait_for(started, what="main job")
        # all jobs complete -> the supervisor exits on its own
        assert proc.wait(timeout=30) == 0
        lines = [
            ln for ln in log_file.read_text().splitlines() if ln.strip()
        ]
        assert lines, "json log file is empty"
        for ln in lines:
            entry = jsonlib.loads(ln)
            assert {"time", "level", "msg"} <= set(entry)
        # the event flow is visible in the structured log
        assert any("main" in e for e in lines)
    finally:
        _teardown_cli(proc)
