"""Token-shard data loading: memmap windows, deterministic resume,
device prefetch, trainer integration (workload/data.py)."""
import numpy as np
import pytest

from containerpilot_tpu.workload.data import (
    DevicePrefetcher,
    TokenShardDataset,
    write_token_shards,
)


@pytest.fixture()
def shard_dir(tmp_path):
    # 3 shards x 1000 tokens of a recognizable ramp
    tokens = np.arange(3000, dtype=np.int32) % 255
    write_token_shards(tokens, str(tmp_path / "data"), shard_size=1000)
    return str(tmp_path / "data")


def test_windows_cover_shards_without_crossing(shard_dir):
    ds = TokenShardDataset(shard_dir, seq_len=9, batch_size=2)
    # 1000 // 10 = 100 windows per shard, never straddling a boundary
    assert ds.n_windows == 300
    batch = ds.batch_at(0)
    assert batch.shape == (2, 10)
    assert batch.dtype == np.int32
    # every window is a contiguous ramp slice (mod the 255 wrap)
    for row in batch:
        deltas = np.diff(row.astype(np.int64)) % 255
        assert (deltas == 1).all()


def test_batches_are_deterministic_and_resumable(shard_dir):
    ds = TokenShardDataset(shard_dir, seq_len=9, batch_size=4, seed=7)
    ds2 = TokenShardDataset(shard_dir, seq_len=9, batch_size=4, seed=7)
    for step in (0, 1, 17, 300):
        np.testing.assert_array_equal(ds.batch_at(step), ds2.batch_at(step))
    # a "resumed" iterator continues the exact stream
    it = ds.batches(start_step=5)
    np.testing.assert_array_equal(next(it), ds.batch_at(5))
    np.testing.assert_array_equal(next(it), ds.batch_at(6))
    # different seeds see different orders
    ds3 = TokenShardDataset(shard_dir, seq_len=9, batch_size=4, seed=8)
    assert not np.array_equal(ds3.batch_at(0), ds.batch_at(0))


def test_epoch_order_is_a_permutation(shard_dir):
    ds = TokenShardDataset(shard_dir, seq_len=9, batch_size=1)
    starts = set()
    for step in range(ds.n_windows):
        starts.add(int(ds.batch_at(step)[0, 0]))
    # one epoch of batch-1 steps touches every distinct window start
    # value (ramp mod 255 collapses some, so compare against truth)
    truth = {int(ds._window(i)[0]) for i in range(ds.n_windows)}
    assert starts == truth


def test_validates_empty_and_short(tmp_path):
    with pytest.raises(FileNotFoundError):
        TokenShardDataset(str(tmp_path), seq_len=8, batch_size=1)
    write_token_shards(np.arange(4), str(tmp_path / "tiny"))
    with pytest.raises(ValueError, match="shorter than"):
        TokenShardDataset(str(tmp_path / "tiny"), seq_len=8, batch_size=1)


def test_vocab_range_check(shard_dir):
    """A vocab/shard mismatch must fail loudly — JAX clamps the
    embedding gather, so silence means training on garbage."""
    ok = TokenShardDataset(shard_dir, seq_len=9, batch_size=2,
                           vocab_size=255)
    ok.batch_at(0)  # ids are 0..254: fine
    bad = TokenShardDataset(shard_dir, seq_len=9, batch_size=2,
                            vocab_size=100)
    with pytest.raises(ValueError, match="out of range"):
        bad.batch_at(0)


def test_prefetcher_propagates_worker_death(shard_dir):
    """A dying worker must fail next(), never hang it."""
    ds = TokenShardDataset(shard_dir, seq_len=9, batch_size=2,
                           vocab_size=10)  # every batch raises
    pf = DevicePrefetcher(ds, start_step=0)
    try:
        with pytest.raises(RuntimeError, match="worker died"):
            pf.next()
    finally:
        pf.stop()


def test_device_prefetcher_orders_and_stops(shard_dir):
    import jax.numpy as jnp

    ds = TokenShardDataset(shard_dir, seq_len=9, batch_size=2)
    pf = DevicePrefetcher(ds, start_step=3, depth=2)
    try:
        for expect in (3, 4, 5):
            step, batch = pf.next()
            assert step == expect
            assert isinstance(batch, jnp.ndarray)
            np.testing.assert_array_equal(
                np.asarray(batch), ds.batch_at(expect)
            )
    finally:
        pf.stop()


def test_trainer_runs_on_token_shards(tmp_path, capsys):
    """End-to-end: the supervised trainer consumes real shards."""
    import sys

    import jax

    from containerpilot_tpu.workload.train import main

    tokens = np.random.default_rng(0).integers(
        0, 128, size=20_000, dtype=np.int32
    )
    data_dir = str(tmp_path / "data")
    write_token_shards(tokens, data_dir, shard_size=10_000)
    argv = sys.argv
    sys.argv = [
        "train", "--steps", "3", "--batch", "2", "--seq-len", "32",
        "--d-model", "64", "--n-layers", "1", "--n-heads", "4",
        "--vocab", "128", "--data-dir", data_dir,
    ]
    try:
        assert main() == 0
    finally:
        sys.argv = argv
    out = capsys.readouterr().out
    assert "train windows" in out
    assert "step 1:" in out


def test_trainer_profiler_trace(tmp_path):
    """--profile-dir captures an XLA trace of steady-state steps."""
    import os
    import sys

    from containerpilot_tpu.workload.train import main

    prof = str(tmp_path / "prof")
    argv = sys.argv
    sys.argv = [
        "train", "--steps", "4", "--batch", "2", "--seq-len", "16",
        "--d-model", "64", "--n-layers", "1", "--n-heads", "4",
        "--vocab", "64", "--profile-dir", prof, "--profile-steps", "2",
    ]
    try:
        assert main() == 0
    finally:
        sys.argv = argv
    traces = []
    for root, _dirs, files in os.walk(prof):
        traces += [f for f in files if f.endswith((".pb", ".json.gz", ".xplane.pb"))]
    assert traces, f"no trace files under {prof}"


def test_holdout_split_is_disjoint_and_served(tmp_path):
    """Held-out windows never appear in the training order and come
    back in fixed order from eval_batch."""
    # globally-unique token values so window CONTENT identifies the
    # window (the shared %255 ramp fixture has content collisions)
    write_token_shards(
        np.arange(3000, dtype=np.int32), str(tmp_path / "u"),
        shard_size=1000,
    )
    ds = TokenShardDataset(
        str(tmp_path / "u"), seq_len=9, batch_size=1, holdout_windows=20
    )
    assert ds.n_windows == 280 and ds.holdout_windows == 20
    assert ds.n_eval_batches == 20
    # eval always serves the same windows, identified by content
    def window_key(row):
        return tuple(int(x) for x in row)

    eval_windows = {
        window_key(ds.eval_batch(i)[0]) for i in range(ds.n_eval_batches)
    }
    assert eval_windows == {
        window_key(ds.eval_batch(i)[0]) for i in range(ds.n_eval_batches)
    }
    # training batches OBSERVED over two-plus epochs never serve a
    # held-out window (content comparison, so an indexing regression
    # in batch_at can't sneak past)
    for step in range(2 * ds.n_windows + 5):
        assert window_key(ds.batch_at(step)[0]) not in eval_windows, step
    with pytest.raises(ValueError, match="holdout_windows"):
        TokenShardDataset(str(tmp_path / "u"), 9, 1, holdout_windows=300)
    with pytest.raises(ValueError, match="no holdout"):
        TokenShardDataset(str(tmp_path / "u"), 9, 1).eval_batch(0)


def test_trainer_eval_loop(tmp_path, capsys):
    """--eval-every reports a held-out loss during a shard-fed run."""
    import sys

    from containerpilot_tpu.workload.train import main

    tokens = np.random.default_rng(1).integers(
        0, 64, size=8_000, dtype=np.int32
    )
    data_dir = str(tmp_path / "data")
    write_token_shards(tokens, data_dir, shard_size=4_000)
    argv = sys.argv
    sys.argv = [
        "train", "--steps", "4", "--batch", "2", "--seq-len", "16",
        "--d-model", "64", "--n-layers", "1", "--n-heads", "4",
        "--vocab", "64", "--data-dir", data_dir,
        "--eval-every", "2", "--eval-holdout", "6",
    ]
    try:
        assert main() == 0
    finally:
        sys.argv = argv
    out = capsys.readouterr().out
    assert "eval_loss=" in out
    assert "+6 held out" in out


def test_evaluate_cli_scores_checkpoint(tmp_path, capsys):
    """Train a few steps with checkpointing, then score the
    checkpoint with the standalone eval CLI: finite loss, matching
    perplexity, and the chunked-loss path agrees with whole-logits."""
    import json as json_mod
    import sys

    from containerpilot_tpu.workload.evaluate import main as eval_main
    from containerpilot_tpu.workload.train import main as train_main

    tokens = np.random.default_rng(1).integers(
        0, 128, size=30_000, dtype=np.int32
    )
    data_dir = str(tmp_path / "data")
    write_token_shards(tokens, data_dir, shard_size=10_000)
    ckpt = str(tmp_path / "ckpt")
    model_flags = [
        "--batch", "2", "--seq-len", "32", "--d-model", "64",
        "--n-layers", "1", "--n-heads", "4", "--vocab", "128",
    ]
    argv = sys.argv
    sys.argv = [
        "train", "--steps", "3", "--data-dir", data_dir,
        "--checkpoint-dir", ckpt, "--checkpoint-every", "3",
        "--eval-holdout", "8",
    ] + model_flags
    try:
        assert train_main() == 0
    finally:
        sys.argv = argv
    capsys.readouterr()

    def run_eval(extra):
        old = sys.argv
        sys.argv = [
            "evaluate", "--checkpoint-dir", ckpt, "--data-dir",
            data_dir, "--eval-holdout", "8",
        ] + model_flags + extra
        try:
            assert eval_main() == 0
        finally:
            sys.argv = old
        return json_mod.loads(capsys.readouterr().out.strip())

    report = run_eval([])
    assert report["checkpoint_step"] == 3
    assert report["split"] == "holdout" and report["batches"] >= 1
    assert 0 < report["eval_loss"] < 20
    np.testing.assert_allclose(
        report["perplexity"], np.exp(report["eval_loss"]), rtol=1e-3
    )
    chunked = run_eval(["--loss-chunk", "8"])
    np.testing.assert_allclose(
        chunked["eval_loss"], report["eval_loss"], rtol=1e-5
    )
    head = run_eval(["--eval-holdout", "0", "--max-batches", "2"])
    assert head["split"] == "head" and head["batches"] == 2


def test_evaluate_cli_ema_honesty(tmp_path, capsys):
    """--use-ema reports "ema": true only when the checkpoint really
    carries a shadow; a non-EMA checkpoint falls back to raw params
    and says so."""
    import json as json_mod
    import sys

    from containerpilot_tpu.workload.evaluate import main as eval_main
    from containerpilot_tpu.workload.train import main as train_main

    tokens = np.random.default_rng(2).integers(
        0, 128, size=20_000, dtype=np.int32
    )
    data_dir = str(tmp_path / "data")
    write_token_shards(tokens, data_dir, shard_size=10_000)
    model_flags = [
        "--batch", "2", "--seq-len", "32", "--d-model", "64",
        "--n-layers", "1", "--n-heads", "4", "--vocab", "128",
    ]

    def train(ckpt, extra):
        old = sys.argv
        sys.argv = [
            "train", "--steps", "2", "--data-dir", data_dir,
            "--checkpoint-dir", ckpt, "--checkpoint-every", "2",
        ] + model_flags + extra
        try:
            assert train_main() == 0
        finally:
            sys.argv = old
        capsys.readouterr()

    def evaluate(ckpt):
        old = sys.argv
        sys.argv = [
            "evaluate", "--checkpoint-dir", ckpt, "--data-dir",
            data_dir, "--eval-holdout", "8", "--use-ema",
            "--max-batches", "1",
        ] + model_flags
        try:
            assert eval_main() == 0
        finally:
            sys.argv = old
        return json_mod.loads(capsys.readouterr().out.strip())

    ema_ckpt = str(tmp_path / "ema")
    train(ema_ckpt, ["--ema-decay", "0.9"])
    assert evaluate(ema_ckpt)["ema"] is True

    raw_ckpt = str(tmp_path / "raw")
    train(raw_ckpt, [])
    assert evaluate(raw_ckpt)["ema"] is False
