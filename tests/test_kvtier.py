"""Fleet-wide KV reuse units: the host-RAM spill tier, the prefix
digest codec, and the ``reuse_admission`` edge cases the tier must
not break (serve_prefix.py's match-then-evicted window, readmit under
concurrent evictions, byte-budget enforcement).

The spill tier moves real device arrays through
``jax.device_get``/``device_put``, so this module rides the workload
tier (conftest pins the CPU platform before jax imports).
"""
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from containerpilot_tpu.kvtier import (
    DIGEST_MAX_BYTES,
    FP_TOKENS,
    HostSpillTier,
    encode_fingerprints,
    parse_digest,
    parse_kv_counters,
    parse_kv_note,
    prefix_fingerprint,
)
from containerpilot_tpu.workload.serve_prefix import (
    BUCKET,
    MIN_REUSE,
    PrefixCache,
    plan_reuse,
)


def _entry(tag: int, rows: int = 8) -> dict:
    """A fake KV pytree: deterministic contents, predictable bytes
    (PrefixCache/HostSpillTier treat entries as opaque)."""
    base = jnp.full((rows, 16), tag, jnp.float32)
    return {"k": base, "v": base + 1, "pos": jnp.asarray(rows, jnp.int32)}


def _entry_bytes(rows: int = 8) -> int:
    return 2 * rows * 16 * 4 + 4


# -- digest codec (pure host) -------------------------------------------


def test_prefix_fingerprint_contract():
    row = list(range(100, 100 + FP_TOKENS))
    fp = prefix_fingerprint(row)
    assert fp is not None and 0 <= fp <= 0xFFFFFFFF
    # stable across calls and processes (blake2b, not hash())
    assert prefix_fingerprint(row) == fp
    # the tail doesn't matter: only the first FP_TOKENS ids hash
    assert prefix_fingerprint(row + [1, 2, 3]) == fp
    # a different prefix fingerprint differs
    assert prefix_fingerprint([7] + row[1:]) != fp
    # too short to ever be reused -> never advertised
    assert prefix_fingerprint(row[: FP_TOKENS - 1]) is None
    # FP_TOKENS tracks the reuse floor by design
    assert FP_TOKENS == MIN_REUSE


def test_digest_roundtrip_and_truncation():
    fps = {1, 0xFFFFFFFF, 0xDEADBEEF, 42}
    raw = encode_fingerprints(7, fps)
    version, parsed = parse_digest(raw)
    assert version == 7 and parsed == frozenset(fps)
    # equal sets encode identically (sorted)
    assert raw == encode_fingerprints(7, reversed(sorted(fps)))
    # size bound: a huge set truncates to whole fingerprints
    big = encode_fingerprints(1, range(10_000))
    assert len(big) <= DIGEST_MAX_BYTES
    v, kept = parse_digest(big)
    assert v == 1 and 0 < len(kept) < 10_000


@pytest.mark.parametrize("raw", [
    None, 17, "", "x", "v:", "v1", "v1:abc",          # malformed head/body
    "v١:00000001",                                # unicode digit
    "v1:zzzzzzzz",                                     # non-hex body
    "v1:" + "0" * (DIGEST_MAX_BYTES + 8),              # oversized body
])
def test_digest_parse_rejects_garbage(raw):
    assert parse_digest(raw) == (None, frozenset())


def test_kv_note_parsing_is_tolerant():
    note = "ok occ=0.50 kv=3,4,120,2,1 pd=v2:0000002a"
    fields = parse_kv_note(note)
    assert fields["occ"] == "0.50" and fields["pd"] == "v2:0000002a"
    assert parse_kv_counters(fields["kv"]) == {
        "hits": 3, "misses": 4, "tokens_reused": 120,
        "spilled": 2, "readmitted": 1,
    }
    # short / torn values keep the fields that did parse, zero-filled
    assert parse_kv_counters("7,2")["hits"] == 7
    assert parse_kv_counters("7,2")["tokens_reused"] == 0
    assert parse_kv_counters("7,x,9")["misses"] == 0
    assert parse_kv_counters(None) == parse_kv_counters("")
    assert parse_kv_note(None) == {}
    assert parse_kv_note("just words no pairs") == {}


# -- host spill tier ----------------------------------------------------


def test_spill_roundtrip_is_byte_exact():
    tier = HostSpillTier(1 << 20)
    entry = _entry(3)
    assert tier.put((1, 2, 3), entry)
    back = tier.take((1, 2, 3))
    assert back is not None
    for leaf, ref in zip(
        jax.tree_util.tree_leaves(back),
        jax.tree_util.tree_leaves(entry),
    ):
        assert leaf.dtype == ref.dtype
        np.testing.assert_array_equal(np.asarray(leaf), np.asarray(ref))
    assert tier.stats["spilled"] == 1 and tier.stats["readmitted"] == 1


def test_spill_byte_budget_evicts_lru_and_refuses_oversize():
    per = _entry_bytes()
    tier = HostSpillTier(2 * per)  # room for exactly two entries
    for tag in range(4):
        assert tier.put((tag,), _entry(tag))
    assert len(tier) == 2
    assert tier.bytes_used <= tier.max_bytes
    assert tier.stats["evicted"] == 2
    # LRU: the two NEWEST keys survived
    assert tier.take((0,)) is None and tier.take((1,)) is None
    assert tier.take((2,)) is not None and tier.take((3,)) is not None
    # an entry larger than the whole budget is refused, not stored
    big = HostSpillTier(per - 1)
    assert not big.put((9,), _entry(9))
    assert big.stats["refused"] == 1 and len(big) == 0
    # re-putting an existing key replaces, never double-counts bytes
    tier.put((5,), _entry(5))
    tier.put((5,), _entry(6))
    assert len(tier) == 1 and tier.bytes_used == per


def test_spill_candidates_bucket_by_fingerprint():
    """The match scan consults the tier by fingerprint bucket, not a
    full key scan: only keys sharing the row's first-FP_TOKENS ids
    (the reuse floor) come back, and the index tracks every insert,
    take, replacement, and budget eviction."""
    tier = HostSpillTier(1 << 20)
    key_a = tuple(range(FP_TOKENS)) + (1, 2)
    key_a2 = tuple(range(FP_TOKENS)) + (9,)   # same first-16 ids
    key_b = tuple(range(50, 50 + FP_TOKENS))  # different prefix
    for key in (key_a, key_a2, key_b):
        assert tier.put(key, _entry(1))
    fp_a = prefix_fingerprint(list(key_a))
    assert set(tier.candidates(fp_a)) == {key_a, key_a2}
    assert tier.candidates(prefix_fingerprint(list(key_b))) == [key_b]
    assert tier.candidates(None) == []
    assert tier.candidates(0x12345678) == []
    # take unindexes
    assert tier.take(key_a) is not None
    assert set(tier.candidates(fp_a)) == {key_a2}
    # budget eviction unindexes the LRU victim
    per = _entry_bytes()
    tight = HostSpillTier(per)
    tight.put(key_a, _entry(1))
    tight.put(key_b, _entry(2))  # evicts key_a
    assert tight.candidates(fp_a) == []
    assert tight.candidates(prefix_fingerprint(list(key_b))) == [key_b]


def test_spill_take_serves_a_key_exactly_once():
    tier = HostSpillTier(1 << 20)
    tier.put((1,), _entry(1))
    assert tier.take((1,)) is not None
    # a second take (concurrent readmit racing this one) misses
    assert tier.take((1,)) is None
    assert tier.stats["misses"] == 1
    assert tier.take((404,)) is None
    assert tier.stats["misses"] == 2


# -- prefix cache + spill integration -----------------------------------


def test_prefix_cache_spills_on_eviction_and_readmits():
    pc = PrefixCache(1, spill=HostSpillTier(1 << 20))
    key_a = tuple(range(MIN_REUSE + 4))
    key_b = tuple(range(100, 100 + MIN_REUSE))
    pc.store(key_a, _entry(1))
    pc.store(key_b, _entry(2))  # device LRU (1 entry) evicts A -> spill
    assert pc.stats["spilled"] == 1
    assert pc.stats["spill_bytes"] > 0
    # the spilled key still matches (best_match scans both tiers)
    n, key = pc.best_match(list(key_a) + [1, 2])
    assert key == key_a and n == len(key_a)
    # fetch readmits it to the device LRU as MRU (spilling B in turn)
    got = pc.get(key_a)
    assert got is not None
    assert pc.stats["readmitted"] == 1
    assert pc.readmit_seconds > 0.0
    with pc._lock:
        assert list(pc._cache) == [key_a]
    # byte parity through the spill roundtrip
    np.testing.assert_array_equal(
        np.asarray(got["k"]), np.asarray(_entry(1)["k"])
    )


def test_match_then_evicted_between_match_and_fetch():
    """The serve_prefix.py get() contract: a key evicted from BOTH
    tiers after the match scan but before the fetch returns None —
    the caller re-prefills cold instead of crashing or double-using
    a freed entry."""
    pc = PrefixCache(1, spill=HostSpillTier(1 << 20))
    key = tuple(range(MIN_REUSE))
    pc.store(key, _entry(1))
    n, matched = pc.best_match(list(key))
    assert matched == key
    # the race window: another request's store pushes it to spill...
    pc.store(tuple(range(50, 50 + MIN_REUSE)), _entry(2))
    # ...and a concurrent readmit drains it from the spill tier too
    assert pc.spill.take(key) is not None
    assert pc.get(matched) is None
    # the cold path then counts a miss through plan_reuse
    reuse, base = plan_reuse(pc, list(key) + [1] * BUCKET)
    assert (reuse, base) == (0, None)


def test_reuse_admission_counts_miss_when_base_vanishes():
    """reuse_admission must answer None (cold prefill) when the
    matched base disappears between match and fetch — the eviction
    window with a spill tier attached is the same contract as
    without one."""
    from containerpilot_tpu.workload.serve_prefix import reuse_admission

    class RacingCache(PrefixCache):
        """Simulates a concurrent eviction winning the window: every
        fetch finds both tiers already drained."""

        def get(self, key):
            with self._lock:
                self._cache.pop(key, None)
            if self.spill is not None:
                self.spill.take(key)
            return super().get(key)

    pc = RacingCache(2, spill=HostSpillTier(1 << 20))
    key = tuple(range(MIN_REUSE + BUCKET))
    pc.store(key, _entry(1))
    hit = reuse_admission(
        pc, list(key) + [3] * BUCKET, cfg=None, params=None
    )
    assert hit is None
    assert pc.stats["misses"] == 1 and pc.stats["hits"] == 0


def test_readmit_under_concurrent_evictions():
    """Stores (spilling under a tight budget) race gets (readmitting)
    across threads — the locked index must neither corrupt nor
    double-serve; every get returns the key's own bytes or None."""
    per = _entry_bytes()
    pc = PrefixCache(1, spill=HostSpillTier(3 * per))
    hot = tuple(range(MIN_REUSE))
    pc.store(hot, _entry(7))
    stop = threading.Event()
    errors = []

    def churn():
        tag = 100
        try:
            while not stop.is_set():
                tag += 1
                pc.store(
                    tuple(range(tag * 50, tag * 50 + MIN_REUSE)),
                    _entry(tag % 50),
                )
        except Exception as exc:  # pragma: no cover - the assertion
            errors.append(exc)

    t = threading.Thread(target=churn, daemon=True)
    t.start()
    served = 0
    try:
        for _ in range(200):
            got = pc.get(hot)
            if got is not None:
                served += 1
                np.testing.assert_array_equal(
                    np.asarray(got["k"]), np.asarray(_entry(7)["k"])
                )
                pc.store(hot, got)  # keep it in play
            else:
                # gone from both tiers (churn outran the budget):
                # the cold path re-prefills and re-stores, exactly
                # what a real miss does
                pc.store(hot, _entry(7))
    finally:
        stop.set()
        t.join(timeout=10)
    assert not errors
    assert served > 0
    # accounting stayed coherent under the churn
    assert pc.spill.bytes_used <= pc.spill.max_bytes
    assert pc.stats["readmitted"] == pc.spill.stats["readmitted"]


def test_digest_is_versioned_and_memoized():
    pc = PrefixCache(2, spill=HostSpillTier(1 << 20))
    assert parse_digest(pc.digest()) == (0, frozenset())
    key = tuple(range(MIN_REUSE))
    pc.store(key, _entry(1))
    v1, fps1 = parse_digest(pc.digest())
    assert fps1 == {prefix_fingerprint(key)}
    assert pc.digest() is pc.digest()  # memoized per version
    # a spilled entry stays advertised (it is still warm, host-side)
    pc.store(tuple(range(60, 60 + MIN_REUSE)), _entry(2))
    pc.store(tuple(range(90, 90 + MIN_REUSE)), _entry(3))
    v2, fps2 = parse_digest(pc.digest())
    assert v2 > v1 and prefix_fingerprint(key) in fps2
    assert len(fps2) == 3
    # short keys (< FP_TOKENS) are never advertised
    short = PrefixCache(2)
    short.store((1, 2, 3), _entry(1))
    assert parse_digest(short.digest())[1] == frozenset()


def test_spill_disabled_keeps_stats_schema_zeroed():
    """/v1/model schema stability: without a tier the spill fields
    exist and stay zero (the PR 1 pod-boot discipline)."""
    pc = PrefixCache(1)
    for tag in range(3):
        pc.store(tuple(range(tag * 40, tag * 40 + MIN_REUSE)), _entry(tag))
    assert pc.stats["spilled"] == 0
    assert pc.stats["readmitted"] == 0
    assert pc.stats["spill_bytes"] == 0
    assert pc.get(tuple(range(MIN_REUSE))) is None  # dropped, not spilled


def test_reuse_admission_readmits_from_spill_byte_parity():
    """End to end on a real model: a server whose device LRU holds ONE
    entry + a spill tier produces byte-identical tokens to a server
    with a big device LRU — the host roundtrip must be invisible to
    the rewind+extend protocol."""
    from types import SimpleNamespace

    from containerpilot_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )
    from containerpilot_tpu.workload.serve_prefix import (
        generate_with_prefix,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=128, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)

    def srv(pc):
        return SimpleNamespace(
            cfg=cfg, params=params, max_len=128, prefill_chunk=0,
            prefix_cache=pc, batch_stats={"calls": 0, "rows": 0},
        )

    spilling = srv(PrefixCache(1, spill=HostSpillTier(1 << 20)))
    roomy = srv(PrefixCache(4))

    turn_a = list(range(1, 33))          # 32-token history A
    turn_b = [9] * 32                    # unrelated history B
    turn_a2 = turn_a + [50] * 16         # A's next turn

    outs = {}
    for name, s in (("spilling", spilling), ("roomy", roomy)):
        outs[name] = [
            generate_with_prefix(s, turn_a, 8, 0.0, 0, 0.0, -1, 0),
            generate_with_prefix(s, turn_b, 8, 0.0, 0, 0.0, -1, 0),
            generate_with_prefix(s, turn_a2, 8, 0.0, 0, 0.0, -1, 0),
        ]
    assert outs["spilling"] == outs["roomy"]
    stats = spilling.prefix_cache.stats
    # A was evicted to host RAM by B, then readmitted for turn 2
    assert stats["spilled"] >= 1, stats
    assert stats["readmitted"] == 1, stats
    assert stats["hits"] == 1, stats
    assert stats["tokens_reused"] >= 16, stats
    # the roomy server reused straight from device: same hit account
    assert roomy.prefix_cache.stats["hits"] == 1
    assert roomy.prefix_cache.stats["readmitted"] == 0
