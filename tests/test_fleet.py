"""Fleet subsystem tests: routing units, connection-pool behavior,
drain hook, catalog robustness, control-plane drain, and the
two-replica gateway integration scenario (drain mid-traffic, zero
client-visible 5xx).

The gateway unit tests run against stub HTTP servers (no JAX); the
integration test boots two real tiny InferenceServers behind a
FleetGateway on the CPU backend.
"""
import asyncio
import json
import socket
import time
import urllib.error
import urllib.request

import pytest

from containerpilot_tpu.discovery import (
    FileCatalogBackend,
    NoopBackend,
    ServiceRegistration,
)
from containerpilot_tpu.fleet import FleetGateway, FleetMember
from containerpilot_tpu.fleet.gateway import Replica
from containerpilot_tpu.utils.http import (
    HTTPServer,
    Response,
    StreamingResponse,
)


def _counter(metric, label: str) -> float:
    return metric.labels(label)._value.get()  # noqa: SLF001


def _post(port, path, payload, timeout=120):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read().decode(), dict(resp.headers)
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read().decode(), dict(exc.headers)


def _get(port, path, timeout=30):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read().decode(), dict(resp.headers)
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read().decode(), dict(exc.headers)


def _register(backend, instance_id, port, name="svc"):
    backend.service_register(
        ServiceRegistration(
            id=instance_id, name=name, port=port, ttl=60,
            address="127.0.0.1",
        ),
        status="passing",
    )


# -- routing units (no servers, no JAX) ---------------------------------


def test_least_outstanding_pick_is_deterministic():
    gw = FleetGateway(NoopBackend(), "svc")
    gw._replicas = {
        "a": Replica("a", "h", 1, outstanding=2),
        "b": Replica("b", "h", 2, outstanding=0),
        "c": Replica("c", "h", 3, outstanding=1),
    }
    assert gw._pick().id == "b"
    assert gw._pick(exclude={"b"}).id == "c"
    assert gw._pick(exclude={"a", "b", "c"}) is None
    # ties break on id, so equal load routes reproducibly
    gw._replicas["b"].outstanding = 1
    assert gw._pick().id == "b"


def test_sticky_affinity_and_drained_away_accounting():
    gw = FleetGateway(NoopBackend(), "svc", affinity="session")
    gw._replicas = {
        "a": Replica("a", "h", 1),
        "b": Replica("b", "h", 2),
    }
    first = gw._route("s:user1")
    # load elsewhere must not move a sticky key
    other_id = "b" if first.id == "a" else "a"
    gw._replicas[other_id].outstanding = 0
    gw._replicas[first.id].outstanding = 5
    assert gw._route("s:user1").id == first.id
    # a pin excluded by one request's retry re-routes THAT request
    # but keeps the pin (warm prefix cache survives a transient
    # failure) and does NOT count as drained_away
    assert gw._route("s:user1", exclude={first.id}).id == other_id
    assert gw._sticky["s:user1"] == first.id
    assert _counter(gw._m_drained, first.id) == 0
    # …but a replica that LEFT the fleet re-pins and counts
    del gw._replicas[first.id]
    rerouted = gw._route("s:user1")
    assert rerouted.id == other_id
    assert gw._sticky["s:user1"] == other_id
    assert _counter(gw._m_drained, first.id) == 1
    # keyless requests never stick
    assert gw._route(None).id == other_id


def test_affinity_key_extraction_modes():
    from containerpilot_tpu.utils.http import Request

    def req(headers=None):
        return Request("POST", "/v1/generate", {}, headers or {}, b"")

    session_gw = FleetGateway(NoopBackend(), "svc", affinity="session")
    prefix_gw = FleetGateway(NoopBackend(), "svc", affinity="prefix")
    none_gw = FleetGateway(NoopBackend(), "svc", affinity="none")

    body = {"session_id": "u1", "tokens": [[1, 2, 3]]}
    assert session_gw._affinity_key(req(), body) == "s:u1"
    assert none_gw._affinity_key(req(), body) is None
    # header beats prompt-derived keys, loses to session_id
    assert session_gw._affinity_key(
        req({"x-affinity-key": "k9"}), {}
    ) == "h:k9"
    # prefix mode: same token prefix -> same key; different -> different
    k1 = prefix_gw._affinity_key(req(), {"tokens": [[1, 2, 3]]})
    k2 = prefix_gw._affinity_key(req(), {"tokens": [[1, 2, 3]]})
    k3 = prefix_gw._affinity_key(req(), {"tokens": [[9, 9, 9]]})
    assert k1 == k2 and k1 != k3 and k1.startswith("p:")
    # session mode does NOT key on prompts (every unique prompt would
    # otherwise occupy a sticky slot)
    assert session_gw._affinity_key(req(), {"tokens": [[1, 2, 3]]}) is None


def test_cache_aware_pick_prefers_warm_within_slack():
    """A replica advertising the request's prefix fingerprint wins
    the pick — but only within cache_slack of the least load, so a
    warm-but-loaded replica never beats a healthy cold one."""
    gw = FleetGateway(NoopBackend(), "svc", cache_slack=2)
    fp = 0xBEEF
    gw._replicas = {
        "a": Replica("a", "h", 1, outstanding=0),
        "b": Replica("b", "h", 2, outstanding=2, digest=frozenset({fp})),
        "c": Replica("c", "h", 3, outstanding=1, digest=frozenset({fp})),
    }
    # no fingerprint: plain least-outstanding
    assert gw._pick().id == "a"
    # warm within slack: least-loaded WARM candidate wins
    assert gw._pick(fp=fp).id == "c"
    assert gw.hint_hits == 1
    # every warm candidate beyond slack: cold pick, counted as a miss
    gw._replicas["b"].outstanding = 3
    gw._replicas["c"].outstanding = 3
    assert gw._pick(fp=fp).id == "a"
    assert gw.hint_misses == 1
    # slack 0 still lets warmth break exact load ties
    tie = FleetGateway(NoopBackend(), "svc", cache_slack=0)
    tie._replicas = {
        "a": Replica("a", "h", 1, outstanding=1),
        "b": Replica("b", "h", 2, outstanding=1, digest=frozenset({fp})),
    }
    assert tie._pick(fp=fp).id == "b"
    # an unknown fingerprint in a digest-publishing fleet is a miss;
    # in a fleet with NO digests at all it is not counted (nothing
    # was in play)
    assert tie._pick(fp=0x1234).id == "a"
    assert tie.hint_misses == 1
    bare = FleetGateway(NoopBackend(), "svc")
    bare._replicas = {"a": Replica("a", "h", 1)}
    assert bare._pick(fp=fp).id == "a"
    assert bare.hint_misses == 0


def test_request_fingerprint_token_rows_only():
    """The gateway fingerprints single token-row bodies exactly the
    way replicas fingerprint cached keys; text prompts and malformed
    bodies keep plain routing (None)."""
    from containerpilot_tpu.kvtier import FP_TOKENS, prefix_fingerprint

    gw = FleetGateway(NoopBackend(), "svc")
    row = list(range(5, 5 + FP_TOKENS + 4))
    assert gw._request_fingerprint(
        {"tokens": [row]}
    ) == prefix_fingerprint(row)
    assert gw._request_fingerprint({"prompt": "text"}) is None
    assert gw._request_fingerprint({"tokens": row}) is None  # flat
    assert gw._request_fingerprint({"tokens": [row, row]}) is None
    assert gw._request_fingerprint({"tokens": [["a"] * 20]}) is None
    assert gw._request_fingerprint(
        {"tokens": [row[: FP_TOKENS - 1]]}
    ) is None
    off = FleetGateway(NoopBackend(), "svc", cache_routing=False)
    assert off._request_fingerprint({"tokens": [row]}) is None


def test_sticky_lru_bound_and_eviction_counter():
    """The sticky table is CAPPED: the oldest pin falls out when a
    new session pins past capacity (it used to grow one entry per
    session forever), and evictions are counted."""
    gw = FleetGateway(NoopBackend(), "svc", sticky_capacity=2)
    gw._replicas = {
        "a": Replica("a", "h", 1),
        "b": Replica("b", "h", 2),
    }
    for n in range(4):
        gw._route(f"s:u{n}")
    assert len(gw._sticky) == 2
    assert gw.sticky_evicted == 2
    assert gw._m_sticky_evicted._value.get() == 2  # noqa: SLF001
    # the survivors are the two newest pins
    assert set(gw._sticky) == {"s:u2", "s:u3"}
    # routing an evicted key simply re-pins (possibly elsewhere);
    # no crash, no drained_away accounting
    assert gw._route("s:u0") is not None
    assert len(gw._sticky) == 2
    with pytest.raises(ValueError):
        FleetGateway(NoopBackend(), "svc", sticky_capacity=0)


def test_apply_notes_updates_kv_state_tolerantly():
    """Heartbeat notes feed routing state: kv= counters and the pd=
    digest parse tolerantly, same-version digests don't churn, and a
    torn note never blanks a warm advertisement."""
    from containerpilot_tpu.kvtier import encode_fingerprints

    gw = FleetGateway(NoopBackend(), "svc")
    r = Replica("a", "h", 1)
    digest = encode_fingerprints(3, {0xAA, 0xBB})
    gw._apply_notes(r, f"ok occ=0.50 kv=4,2,96,1,1 pd={digest}")
    assert r.kv["tokens_reused"] == 96 and r.kv["hits"] == 4
    assert r.digest == frozenset({0xAA, 0xBB})
    assert r.digest_version == 3 and r.digest_at > 0
    stamp = r.digest_at
    # same version: no re-parse churn, stamp untouched
    gw._apply_notes(r, f"ok kv=5,2,97,1,1 pd={digest}")
    assert r.digest_at == stamp and r.kv["hits"] == 5
    # a digest-free or garbage note keeps the previous advertisement,
    # and a torn/malformed kv= must NOT regress the cumulative
    # counters (a zeroed tokens_reused parked by a departure would
    # permanently drop the replica from the fleet-wide gauge)
    gw._apply_notes(r, "ok occ=0.75")
    gw._apply_notes(r, "ok pd=garbage kv=nonsense")
    gw._apply_notes(r, "ok kv=5,2,")      # torn mid-value
    gw._apply_notes(r, "ok kv=5,2,9,1,1")  # truncated digit: 97 -> 9
    assert r.digest == frozenset({0xAA, 0xBB})
    assert r.kv == {
        "hits": 5, "misses": 2, "tokens_reused": 97,
        "spilled": 1, "readmitted": 1,
    }
    # a new version replaces the set
    gw._apply_notes(r, f"ok pd={encode_fingerprints(4, {0xCC})}")
    assert r.digest == frozenset({0xCC}) and r.digest_version == 4


def test_pick_excludes_standby_role():
    """A standby-role replica is warm, catalog-visible capacity that
    the router must NEVER choose — even when it is the least loaded —
    until its post-promotion beat drops the role field."""
    gw = FleetGateway(NoopBackend(), "svc")
    gw._replicas = {
        "a": Replica("a", "h", 1, outstanding=5),
        "sb": Replica("sb", "h", 2, outstanding=0, role="standby"),
    }
    assert gw._pick().id == "a"  # idle standby loses to loaded active
    gw._replicas["a"].role = "standby"
    assert gw._pick() is None    # all-standby fleet routes nowhere
    # promotion (role field absent from the next note) restores it
    gw._apply_notes(gw._replicas["sb"], "ok occ=0.00")
    assert gw._pick().id == "sb"


def test_apply_notes_parses_role_and_compile_cache():
    """role= rides every standby beat and is absent from active
    beats (promotion flips by omission); cc= is kept raw for /fleet
    and adoption; garbage roles default to active."""
    gw = FleetGateway(NoopBackend(), "svc")
    r = Replica("a", "h", 1)
    assert r.role == "active"
    gw._apply_notes(r, "ok occ=0.00 role=standby cc=ab12:%2Ftmp%2Fcc")
    assert r.role == "standby"
    assert r.compile_cache == "ab12:%2Ftmp%2Fcc"
    # a TORN/empty note must keep the previous role: flipping a
    # standby routable off a half-written record would route a poll
    # interval of traffic into its 503s
    gw._apply_notes(r, "")
    gw._apply_notes(r, "ok")
    assert r.role == "standby"
    # the first post-promotion beat has no role field but DID parse
    # (a real beat always carries occ=): active by omission
    gw._apply_notes(r, "ok occ=0.10")
    assert r.role == "active"
    assert r.compile_cache == "ab12:%2Ftmp%2Fcc"  # sticky until replaced
    gw._apply_notes(r, "ok role=gibberish")
    assert r.role == "active"


def test_standby_member_note_and_gateway_capacity(run, tmp_path):
    """Live wiring: a FleetMember fronting a standby-role stub
    advertises role=standby (and cc=) through its TTL beat; the
    gateway's poll excludes it from admission capacity and routing
    while listing it on /fleet — and promotion (role attr flip +
    next beat) brings capacity and routability back."""
    backend = FileCatalogBackend(str(tmp_path / "catalog"))

    class _RoleStub(_StubReplica):
        def __init__(self):
            super().__init__()
            self.role = "standby"

        def compile_cache_note(self):
            return "beef:%2Ftmp%2Fcc"

    async def scenario():
        active = _StubReplica()
        standby = _RoleStub()
        m1 = FleetMember(
            active, backend, "svc", ttl=5, heartbeat_interval=0.05,
            instance_id="r-active",
        )
        m2 = FleetMember(
            standby, backend, "svc", ttl=5, heartbeat_interval=0.05,
            instance_id="r-standby",
        )
        await m1.start()
        await m2.start()
        gw = FleetGateway(
            backend, "svc", "127.0.0.1", 0, poll_interval=0.05,
            admission={"per_replica_inflight": 2},
        )
        await gw.run()
        for _ in range(100):
            if (
                gw.replica_count == 2
                and gw._replicas.get("r-standby") is not None
                and gw._replicas["r-standby"].role == "standby"
            ):
                break
            await asyncio.sleep(0.05)
        assert gw._replicas["r-standby"].role == "standby"
        assert gw._replicas["r-standby"].compile_cache.startswith(
            "beef:"
        )
        # routing: only the active replica is ever picked
        assert gw._pick().id == "r-active"
        # admission capacity: 1 active x 2 inflight, standby excluded
        assert gw._admission.capacity == 2
        # /fleet shows the parked capacity
        status = json.loads(
            (await gw._fleet_status(None)).body
        )
        assert status["standby"] == {
            "count": 1, "ids": ["r-standby"],
        }
        roles = {
            r["id"]: r["role"] for r in status["replicas"]
        }
        assert roles == {
            "r-active": "active", "r-standby": "standby",
        }
        # promote: flip the role; the next beat drops the field and
        # the next poll folds the capacity in
        standby.role = "active"
        for _ in range(100):
            if gw._admission.capacity == 4:
                break
            await asyncio.sleep(0.05)
        assert gw._admission.capacity == 4
        assert gw._replicas["r-standby"].role == "active"
        await gw.stop()
        await m1.stop()
        await m2.stop()

    run(scenario(), timeout=60)


def test_fleet_tokens_reused_survives_replica_departure(run, tmp_path):
    """The fleet-wide tokens_reused gauge folds a departed replica's
    final advertised counter into _reuse_departed instead of
    forgetting it when the record leaves the catalog."""
    backend = FileCatalogBackend(str(tmp_path))

    async def scenario():
        gw = FleetGateway(
            backend, "svc", poll_interval=0.05, empty_poll_threshold=1
        )
        for rid, port in (("r1", 1001), ("r2", 1002)):
            backend.service_register(
                ServiceRegistration(
                    id=rid, name="svc", port=port, ttl=60,
                    address="127.0.0.1",
                ),
                status="passing",
            )
            backend.update_ttl(rid, "ok occ=0.10 kv=1,0,50,0,0", "pass")
        await gw._poll_once()
        assert gw._fleet_tokens_reused() == 100
        assert gw._replicas["r1"].kv["tokens_reused"] == 50
        # r1 leaves the fleet (drain/crash): its contribution stays
        backend.service_deregister("r1")
        backend.update_ttl("r2", "ok occ=0.10 kv=2,0,75,0,0", "pass")
        await gw._poll_once()
        assert set(gw._replicas) == {"r2"}
        assert gw._fleet_tokens_reused() == 50 + 75
        # r1 FLAPS BACK (wedge heal / TTL-starved heartbeat) with its
        # cumulative counter intact: the parked departed copy must be
        # reclaimed, not double-counted
        backend.service_register(
            ServiceRegistration(
                id="r1", name="svc", port=1001, ttl=60,
                address="127.0.0.1",
            ),
            status="passing",
        )
        backend.update_ttl("r1", "ok occ=0.10 kv=1,0,50,0,0", "pass")
        await gw._poll_once()
        assert set(gw._replicas) == {"r1", "r2"}
        assert gw._fleet_tokens_reused() == 50 + 75
        return True

    assert run(scenario())


def test_hedge_threshold_is_learned_per_endpoint():
    """Millisecond /v1/score samples must not set the hedge deadline
    for second-long /v1/generate requests (and vice versa)."""
    from collections import deque

    gw = FleetGateway(NoopBackend(), "svc", hedge_min_ms=1.0)
    gw._replicas = {
        "a": Replica("a", "h", 1),
        "b": Replica("b", "h", 2),
    }
    gw._latencies["score"] = deque([0.002] * 30)
    # no generate samples yet -> no basis to hedge generate
    assert gw._hedge_threshold("generate") is None
    gw._latencies["generate"] = deque([0.5] * 30)
    assert gw._hedge_threshold("generate") >= 0.5
    assert gw._hedge_threshold("score") < 0.01
    # hedging needs somewhere to hedge TO
    del gw._replicas["b"]
    assert gw._hedge_threshold("generate") is None


# -- gateway behavior against stub replicas (no JAX) --------------------


def test_gateway_retries_on_a_different_replica(run, tmp_path):
    """A 503 from the first-picked replica (draining/warming) moves
    the request to another replica; the client sees only the 200."""
    backend = FileCatalogBackend(str(tmp_path))
    calls = {"aaa": 0, "bbb": 0}

    async def scenario():
        draining, healthy = HTTPServer(), HTTPServer()

        async def handler_draining(_req):
            calls["aaa"] += 1
            return Response(
                503, b"draining\n", headers={"Retry-After": "1"}
            )

        async def handler_healthy(_req):
            calls["bbb"] += 1
            return Response(
                200, json.dumps({"tokens": [[9]]}).encode(),
                content_type="application/json",
            )

        draining.route("POST", "/v1/generate", handler_draining)
        healthy.route("POST", "/v1/generate", handler_healthy)
        await draining.start_tcp("127.0.0.1", 0)
        await healthy.start_tcp("127.0.0.1", 0)
        # ids chosen so the load tie breaks to the draining replica
        _register(backend, "aaa", draining.bound_port)
        _register(backend, "bbb", healthy.bound_port)
        gw = FleetGateway(
            backend, "svc", "127.0.0.1", 0,
            poll_interval=0.2, hedge=False, retry_backoff=0.01,
        )
        await gw.run()
        assert gw.replica_count == 2
        status, text, _ = await asyncio.get_event_loop().run_in_executor(
            None, _post, gw.port, "/v1/generate",
            {"tokens": [[1]], "max_new_tokens": 2},
        )
        retried = _counter(gw._m_retried, "aaa")
        await gw.stop()
        await draining.stop()
        await healthy.stop()
        return status, text, retried

    status, text, retried = run(scenario(), timeout=60)
    assert status == 200 and json.loads(text)["tokens"] == [[9]]
    assert calls == {"aaa": 1, "bbb": 1}
    assert retried == 1


def test_gateway_exhausted_retries_surface_503_with_retry_after(
    run, tmp_path
):
    backend = FileCatalogBackend(str(tmp_path))

    async def scenario():
        gw = FleetGateway(
            backend, "svc", "127.0.0.1", 0, poll_interval=5.0,
        )
        await gw.run()  # catalog is empty: no replicas at all
        status, _text, headers = (
            await asyncio.get_event_loop().run_in_executor(
                None, _post, gw.port, "/v1/generate", {"tokens": [[1]]},
            )
        )
        health = await asyncio.get_event_loop().run_in_executor(
            None, _get, gw.port, "/health"
        )
        await gw.stop()
        return status, headers, health

    status, headers, health = run(scenario(), timeout=60)
    assert status == 503
    assert {k.lower(): v for k, v in headers.items()}["retry-after"]
    assert health[0] == 503


def test_gateway_hedges_slow_replica_and_takes_the_fast_result(
    run, tmp_path
):
    """A request still unanswered at the hedge deadline races a second
    replica; the fast replica's answer wins and the slow dispatch is
    cancelled (its connection drops)."""
    backend = FileCatalogBackend(str(tmp_path))

    async def scenario():
        slow, fast = HTTPServer(), HTTPServer()

        async def handler_slow(_req):
            await asyncio.sleep(1.0)
            return Response(200, b'{"who": "slow"}',
                            content_type="application/json")

        async def handler_fast(_req):
            return Response(200, b'{"who": "fast"}',
                            content_type="application/json")

        slow.route("POST", "/v1/generate", handler_slow)
        fast.route("POST", "/v1/generate", handler_fast)
        await slow.start_tcp("127.0.0.1", 0)
        await fast.start_tcp("127.0.0.1", 0)
        _register(backend, "aaa", slow.bound_port)  # tie -> slow first
        _register(backend, "bbb", fast.bound_port)
        gw = FleetGateway(
            backend, "svc", "127.0.0.1", 0,
            poll_interval=5.0, retries=0, hedge_after_ms=80.0,
        )
        await gw.run()
        t0 = time.perf_counter()
        status, text, _ = await asyncio.get_event_loop().run_in_executor(
            None, _post, gw.port, "/v1/generate", {"tokens": [[1]]},
        )
        elapsed = time.perf_counter() - t0
        hedged = _counter(gw._m_hedged, "aaa")
        routed_fast = _counter(gw._m_routed, "bbb")
        await gw.stop()
        await slow.stop()
        await fast.stop()
        return status, text, elapsed, hedged, routed_fast

    status, text, elapsed, hedged, routed_fast = run(
        scenario(), timeout=60
    )
    assert status == 200 and json.loads(text)["who"] == "fast"
    assert elapsed < 0.8, f"hedge did not preempt the slow replica: {elapsed}"
    assert hedged == 1 and routed_fast == 1


# -- gateway connection pool (stub replicas, no JAX) --------------------


def test_gateway_pool_reuses_connections_across_requests(run, tmp_path):
    """Sequential buffered requests ride ONE upstream connection: the
    replica accepts a single connection, the pool counts one miss and
    the rest hits, and /fleet + /metrics expose the counters."""
    backend = FileCatalogBackend(str(tmp_path))

    async def scenario():
        replica = HTTPServer()

        async def handler(_req):
            return Response(
                200, json.dumps({"tokens": [[7]]}).encode(),
                content_type="application/json",
            )

        replica.route("POST", "/v1/generate", handler)
        await replica.start_tcp("127.0.0.1", 0)
        _register(backend, "aaa", replica.bound_port)
        # mux=False: this suite pins the CLASSIC pooled discipline,
        # which stays the fallback for replicas that decline the
        # cp-mux upgrade (the mux paths have their own suite)
        gw = FleetGateway(
            backend, "svc", "127.0.0.1", 0, poll_interval=5.0,
            hedge=False, mux=False,
        )
        await gw.run()
        loop = asyncio.get_event_loop()
        for _ in range(4):
            status, _text, _ = await loop.run_in_executor(
                None, _post, gw.port, "/v1/generate", {"tokens": [[1]]},
            )
            assert status == 200
        fleet_view = await loop.run_in_executor(
            None, _get, gw.port, "/fleet"
        )
        metrics = await loop.run_in_executor(
            None, _get, gw.port, "/metrics"
        )
        stats = gw._pool.stats("aaa")  # noqa: SLF001
        accepted = replica.connections_accepted
        served = replica.requests_served
        await gw.stop()
        await replica.stop()
        return stats, accepted, served, fleet_view, metrics

    stats, accepted, served, fleet_view, metrics = run(
        scenario(), timeout=60
    )
    assert accepted == 1 and served == 4  # one dial, four requests
    assert stats["misses"] == 1 and stats["hits"] == 3
    assert stats["idle"] == 1  # the warm connection went back
    pool_view = {
        r["id"]: r["pool"]
        for r in json.loads(fleet_view[1])["replicas"]
    }
    assert pool_view["aaa"]["hits"] == 3
    assert (
        'containerpilot_gateway_pool_hit_total{replica="aaa"} 3.0'
        in metrics[1]
    )
    assert (
        'containerpilot_gateway_pool_miss_total{replica="aaa"} 1.0'
        in metrics[1]
    )


def test_gateway_pool_evicts_on_deregister(run, tmp_path):
    """Pooled connections to a replica that left the healthy set
    (drain deregisters it) are evicted at the next poll, never
    reused."""
    backend = FileCatalogBackend(str(tmp_path))

    async def scenario():
        replica = HTTPServer()

        async def handler(_req):
            return Response(200, b"{}", content_type="application/json")

        replica.route("POST", "/v1/generate", handler)
        await replica.start_tcp("127.0.0.1", 0)
        _register(backend, "aaa", replica.bound_port)
        gw = FleetGateway(
            backend, "svc", "127.0.0.1", 0, poll_interval=0.1,
            hedge=False, mux=False,
        )
        await gw.run()
        loop = asyncio.get_event_loop()
        status, _, _ = await loop.run_in_executor(
            None, _post, gw.port, "/v1/generate", {"tokens": [[1]]},
        )
        assert status == 200
        assert gw._pool.idle_count("aaa") == 1  # noqa: SLF001
        backend.service_deregister("aaa")
        for _ in range(100):
            if gw.replica_count == 0:
                break
            await asyncio.sleep(0.05)
        idle = gw._pool.idle_count("aaa")  # noqa: SLF001
        evicted = gw._pool.evicted.get("aaa", 0)  # noqa: SLF001
        await gw.stop()
        await replica.stop()
        return idle, evicted

    idle, evicted = run(scenario(), timeout=60)
    assert idle == 0 and evicted == 1


def test_gateway_pool_redials_stale_connection_transparently(
    run, tmp_path
):
    """A pooled connection the replica reaped while idle is detected
    and redialed without the client seeing a failure."""
    backend = FileCatalogBackend(str(tmp_path))

    async def scenario():
        replica = HTTPServer()
        replica.KEEPALIVE_IDLE_TIMEOUT = 0.15

        async def handler(_req):
            return Response(200, b"{}", content_type="application/json")

        replica.route("POST", "/v1/generate", handler)
        await replica.start_tcp("127.0.0.1", 0)
        _register(backend, "aaa", replica.bound_port)
        gw = FleetGateway(
            backend, "svc", "127.0.0.1", 0, poll_interval=5.0,
            hedge=False, mux=False,
        )
        await gw.run()
        loop = asyncio.get_event_loop()
        first, _, _ = await loop.run_in_executor(
            None, _post, gw.port, "/v1/generate", {"tokens": [[1]]},
        )
        await asyncio.sleep(0.4)  # let the replica reap the idle conn
        second, _, _ = await loop.run_in_executor(
            None, _post, gw.port, "/v1/generate", {"tokens": [[1]]},
        )
        stats = gw._pool.stats("aaa")  # noqa: SLF001
        retried = _counter(gw._m_retried, "aaa")  # noqa: SLF001
        await gw.stop()
        await replica.stop()
        return first, second, stats, retried

    first, second, stats, retried = run(scenario(), timeout=60)
    assert first == 200 and second == 200
    # the reap voided the pooled connection: two dials total, the
    # stale one evicted, and NO routing-level retry was consumed
    assert stats["misses"] == 2 and stats["hits"] == 0
    assert stats["evicted"] >= 1
    assert retried == 0


def test_hedge_legs_take_distinct_connections(run, tmp_path):
    """The losing hedge leg's connection is discarded (it may carry a
    half-written response), never pooled; the winner's goes back."""
    backend = FileCatalogBackend(str(tmp_path))

    async def scenario():
        slow, fast = HTTPServer(), HTTPServer()

        async def handler_slow(_req):
            await asyncio.sleep(1.0)
            return Response(200, b'{"who": "slow"}',
                            content_type="application/json")

        async def handler_fast(_req):
            return Response(200, b'{"who": "fast"}',
                            content_type="application/json")

        slow.route("POST", "/v1/generate", handler_slow)
        fast.route("POST", "/v1/generate", handler_fast)
        await slow.start_tcp("127.0.0.1", 0)
        await fast.start_tcp("127.0.0.1", 0)
        _register(backend, "aaa", slow.bound_port)  # tie -> slow first
        _register(backend, "bbb", fast.bound_port)
        gw = FleetGateway(
            backend, "svc", "127.0.0.1", 0,
            poll_interval=5.0, retries=0, hedge_after_ms=80.0,
            mux=False,
        )
        await gw.run()
        status, text, _ = await asyncio.get_event_loop().run_in_executor(
            None, _post, gw.port, "/v1/generate", {"tokens": [[1]]},
        )
        idle_slow = gw._pool.idle_count("aaa")  # noqa: SLF001
        idle_fast = gw._pool.idle_count("bbb")  # noqa: SLF001
        dials = (slow.connections_accepted, fast.connections_accepted)
        await gw.stop()
        await slow.stop()
        await fast.stop()
        return status, text, idle_slow, idle_fast, dials

    status, text, idle_slow, idle_fast, dials = run(
        scenario(), timeout=60
    )
    assert status == 200 and json.loads(text)["who"] == "fast"
    assert dials == (1, 1)  # one private connection per leg
    assert idle_slow == 0  # cancelled leg: discarded, not pooled
    assert idle_fast == 1  # winning leg: released for reuse


# -- satellite bugfixes: upstream response parsing ----------------------


def test_content_length_parsed_strictly():
    """int() and str.isdigit() both accept Unicode digits; the parser
    must not — and garbage must raise instead of silently switching
    to read-to-EOF framing."""
    from containerpilot_tpu.fleet.gateway import (
        UpstreamError,
        _parse_content_length,
    )

    assert _parse_content_length({"content-length": "42"}) == 42
    assert _parse_content_length({}) is None
    for bad in ("١٢٣", "12abc", "-1", "+5", "", "4 2"):
        with pytest.raises(UpstreamError):
            _parse_content_length({"content-length": bad})


async def _raw_replica(respond: bytes):
    """A server that reads one full request, writes ``respond``
    verbatim, and closes — for malformed-upstream scenarios a real
    HTTPServer can't produce."""
    hits = []

    async def handle(reader, writer):
        head = await reader.readuntil(b"\r\n\r\n")
        length = 0
        for line in head.split(b"\r\n"):
            if line.lower().startswith(b"content-length:"):
                length = int(line.split(b":")[1])
        if length:
            await reader.readexactly(length)
        hits.append(1)
        writer.write(respond)
        await writer.drain()
        writer.close()

    server = await asyncio.start_server(handle, "127.0.0.1", 0)
    return server, server.sockets[0].getsockname()[1], hits


def test_replica_dying_after_status_line_is_retried(run, tmp_path):
    """EOF inside the response header block is an UpstreamError (not
    an empty-header 'success'), so the retry path fires and the
    client still gets a 200 from the healthy replica."""
    backend = FileCatalogBackend(str(tmp_path))

    async def scenario():
        broken, broken_port, hits = await _raw_replica(
            b"HTTP/1.1 200 OK\r\n"  # dies mid-header-block
        )
        healthy = HTTPServer()

        async def handler(_req):
            return Response(
                200, json.dumps({"tokens": [[9]]}).encode(),
                content_type="application/json",
            )

        healthy.route("POST", "/v1/generate", handler)
        await healthy.start_tcp("127.0.0.1", 0)
        _register(backend, "aaa", broken_port)  # tie -> broken first
        _register(backend, "bbb", healthy.bound_port)
        gw = FleetGateway(
            backend, "svc", "127.0.0.1", 0,
            poll_interval=5.0, hedge=False, retry_backoff=0.01,
            mux=False,  # pins the HTTP/1.1 response-parsing path
        )
        await gw.run()
        status, text, _ = await asyncio.get_event_loop().run_in_executor(
            None, _post, gw.port, "/v1/generate", {"tokens": [[1]]},
        )
        retried = _counter(gw._m_retried, "aaa")
        await gw.stop()
        broken.close()
        await broken.wait_closed()
        await healthy.stop()
        return status, text, retried, len(hits)

    status, text, retried, hits = run(scenario(), timeout=60)
    assert status == 200 and json.loads(text)["tokens"] == [[9]]
    assert hits == 1 and retried == 1


def test_malformed_content_length_is_retried(run, tmp_path):
    """Garbage Content-Length fails the leg (UpstreamError) instead
    of silently mis-framing the body as read-to-EOF."""
    backend = FileCatalogBackend(str(tmp_path))

    async def scenario():
        broken, broken_port, hits = await _raw_replica(
            b"HTTP/1.1 200 OK\r\nContent-Length: 12abc\r\n\r\nhello"
        )
        healthy = HTTPServer()

        async def handler(_req):
            return Response(
                200, json.dumps({"tokens": [[9]]}).encode(),
                content_type="application/json",
            )

        healthy.route("POST", "/v1/generate", handler)
        await healthy.start_tcp("127.0.0.1", 0)
        _register(backend, "aaa", broken_port)
        _register(backend, "bbb", healthy.bound_port)
        gw = FleetGateway(
            backend, "svc", "127.0.0.1", 0,
            poll_interval=5.0, hedge=False, retry_backoff=0.01,
            mux=False,  # pins the HTTP/1.1 response-parsing path
        )
        await gw.run()
        status, text, _ = await asyncio.get_event_loop().run_in_executor(
            None, _post, gw.port, "/v1/generate", {"tokens": [[1]]},
        )
        retried = _counter(gw._m_retried, "aaa")
        await gw.stop()
        broken.close()
        await broken.wait_closed()
        await healthy.stop()
        return status, text, retried

    status, text, retried = run(scenario(), timeout=60)
    assert status == 200 and json.loads(text)["tokens"] == [[9]]
    assert retried == 1


# -- satellite: filecatalog robustness ----------------------------------


def test_filecatalog_listing_survives_torn_and_leftover_records(tmp_path):
    """Torn JSON (partial NFS write), writer scratch files, and
    records missing required keys are skipped as critical — never an
    exception that hides the healthy peers next to them."""
    backend = FileCatalogBackend(str(tmp_path))
    _register(backend, "good", 8001)
    sdir = tmp_path / "services" / "svc"
    (sdir / "torn.json").write_text('{"id": "torn", "na')
    (sdir / "scratch.json.tmp").write_text("{}")
    (sdir / "nokeys.json").write_text(
        json.dumps({"status": "passing", "expires": time.time() + 60})
    )
    (sdir / "notdict.json").write_text("[1, 2, 3]")
    (sdir / "badport.json").write_text(json.dumps({
        "id": "badport", "name": "svc", "port": "eighty",
        "status": "passing", "expires": time.time() + 60,
    }))
    instances = backend.instances("svc")
    assert [i.id for i in instances] == ["good"]
    did_change, healthy = backend.check_for_upstream_changes("svc")
    assert healthy


# -- member lifecycle (stub server, no JAX) -----------------------------


class _StubReplica:
    """Duck-types the InferenceServer drain surface."""

    def __init__(self):
        self.ready = True
        self.draining = False
        self.inflight = 0
        self.port = 4242

    def enter_maintenance(self):
        self.draining = True

    def exit_maintenance(self):
        self.draining = False


def test_member_heartbeats_and_ttl_expiry(run, tmp_path):
    backend = FileCatalogBackend(str(tmp_path))

    async def scenario():
        stub = _StubReplica()
        member = FleetMember(
            stub, backend, "svc", ttl=1, heartbeat_interval=0.05,
            instance_id="r1",
        )
        await member.start()
        for _ in range(100):
            if backend.instances("svc"):
                break
            await asyncio.sleep(0.02)
        assert [i.id for i in backend.instances("svc")] == ["r1"]
        # a replica that stops being ready stops beating; the record
        # flips critical by TTL expiry, like a wedged job
        stub.ready = False
        await asyncio.sleep(1.3)
        assert backend.instances("svc") == []
        # recovery: ready again -> next heartbeat revives the record
        stub.ready = True
        for _ in range(100):
            if backend.instances("svc"):
                break
            await asyncio.sleep(0.02)
        assert [i.id for i in backend.instances("svc")] == ["r1"]
        await member.stop()
        assert backend.instances("svc") == []

    run(scenario(), timeout=60)


def test_member_drains_via_control_plane(run, tmp_path):
    """POST /v3/maintenance/enable on the control socket drains the
    replica: maintenance flag set, catalog record gone; disable
    resumes and the next heartbeat re-registers."""
    from containerpilot_tpu.client import ControlClient
    from containerpilot_tpu.control import ControlConfig, ControlServer
    from containerpilot_tpu.events import EventBus

    socket_path = str(tmp_path / "cp.sock")
    backend = FileCatalogBackend(str(tmp_path / "catalog"))

    async def scenario():
        bus = EventBus()
        control = ControlServer(ControlConfig({"socket": socket_path}))
        await control.run(bus)
        stub = _StubReplica()
        member = FleetMember(
            stub, backend, "svc", ttl=2, heartbeat_interval=0.05,
            instance_id="r1",
        )
        await member.start()
        member.attach_bus(bus)
        loop = asyncio.get_event_loop()
        client = ControlClient(socket_path)
        for _ in range(100):
            if backend.instances("svc"):
                break
            await asyncio.sleep(0.02)
        assert backend.instances("svc")

        await loop.run_in_executor(None, client.set_maintenance, True)
        for _ in range(100):
            if stub.draining and not backend.instances("svc"):
                break
            await asyncio.sleep(0.02)
        assert stub.draining
        assert backend.instances("svc") == []
        assert await loop.run_in_executor(
            None, client.get_maintenance_status
        )

        await loop.run_in_executor(None, client.set_maintenance, False)
        for _ in range(100):
            if not stub.draining and backend.instances("svc"):
                break
            await asyncio.sleep(0.02)
        assert not stub.draining
        assert backend.instances("svc")

        await member.stop()
        await control.stop()

    run(scenario(), timeout=60)


# -- serve.py drain hook (tiny model, CPU) ------------------------------


def test_inference_server_drain_hook(run):
    import jax
    import jax.numpy as jnp

    from containerpilot_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )
    from containerpilot_tpu.workload.serve import InferenceServer

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    server = InferenceServer(cfg, params, "127.0.0.1", 0, max_len=32)

    async def scenario():
        loop = asyncio.get_event_loop()
        await server.run()
        body = {"tokens": [[1, 2, 3]], "max_new_tokens": 4}
        before = await loop.run_in_executor(
            None, _post, server.port, "/v1/generate", body
        )
        server.enter_maintenance()
        health = await loop.run_in_executor(
            None, _get, server.port, "/health"
        )
        rejected = await loop.run_in_executor(
            None, _post, server.port, "/v1/generate", body
        )
        # reads stay up for the replica's last consumers
        model = await loop.run_in_executor(
            None, _get, server.port, "/v1/model"
        )
        score = await loop.run_in_executor(
            None, _post, server.port, "/v1/score",
            {"tokens": [[1, 2, 3, 4]]},
        )
        server.exit_maintenance()
        after = await loop.run_in_executor(
            None, _post, server.port, "/v1/generate", body
        )
        await server.stop()
        return before, health, rejected, model, score, after

    before, health, rejected, model, score, after = run(
        scenario(), timeout=300
    )
    assert before[0] == 200
    assert health[0] == 503 and "draining" in health[1]
    assert rejected[0] == 503
    assert {k.lower(): v for k, v in rejected[2].items()}["retry-after"]
    assert model[0] == 200 and json.loads(model[1])["draining"] is True
    assert score[0] == 200
    assert after[0] == 200
    assert server.inflight == 0


# -- the tier-1 integration scenario ------------------------------------


def test_fleet_gateway_drain_mid_traffic_zero_5xx(run, tmp_path):
    """Two replicas behind the gateway; one drains mid-traffic. Every
    client request completes 200 (the drain 503s are absorbed by
    retry-on-another-replica), the drained replica leaves the healthy
    set immediately, and SSE streaming keeps working through the
    gateway afterwards."""
    import jax
    import jax.numpy as jnp

    from containerpilot_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )
    from containerpilot_tpu.workload.serve import InferenceServer

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    replica1 = InferenceServer(
        cfg, params, "127.0.0.1", 0, max_len=64, slots=2, slot_chunk=4
    )
    replica2 = InferenceServer(
        cfg, params, "127.0.0.1", 0, max_len=64, slots=2, slot_chunk=4
    )
    backend = FileCatalogBackend(str(tmp_path))

    async def scenario():
        loop = asyncio.get_event_loop()
        await replica1.run()
        await replica2.run()
        member1 = FleetMember(
            replica1, backend, "inference", ttl=5,
            heartbeat_interval=0.1, instance_id="replica-1",
        )
        member2 = FleetMember(
            replica2, backend, "inference", ttl=5,
            heartbeat_interval=0.1, instance_id="replica-2",
        )
        await member1.start()
        await member2.start()
        gateway = FleetGateway(
            backend, "inference", "127.0.0.1", 0,
            poll_interval=0.2, hedge=False, retry_backoff=0.01,
        )
        await gateway.run()
        for _ in range(100):
            if gateway.replica_count == 2:
                break
            await asyncio.sleep(0.05)
        assert gateway.replica_count == 2

        results = []

        async def client_loop(worker, n):
            for i in range(n):
                status, text, _ = await loop.run_in_executor(
                    None, _post, gateway.port, "/v1/generate",
                    {
                        "tokens": [[1, 2, 3, 4]],
                        "max_new_tokens": 16,
                        "seed": worker * 100 + i,
                    },
                )
                results.append((status, text))

        clients = [
            asyncio.ensure_future(client_loop(w, 6)) for w in range(3)
        ]
        await asyncio.sleep(0.1)  # let traffic get in flight

        drained = await member1.drain()
        assert drained is True
        assert replica1.draining
        # the drained replica is out of the healthy set immediately
        # (deregistration, not TTL decay): well within one gateway
        # poll interval
        instances = await loop.run_in_executor(
            None, backend.instances, "inference"
        )
        assert [i.id for i in instances] == ["replica-2"]

        await asyncio.gather(*clients)
        assert len(results) == 18
        assert all(status == 200 for status, _ in results), [
            status for status, _ in results
        ]
        for _status, text in results:
            out = json.loads(text)["tokens"]
            assert len(out) == 1 and 1 <= len(out[0]) <= 16

        # the gateway's routing set converges to the one survivor
        for _ in range(50):
            if gateway.replica_count == 1:
                break
            await asyncio.sleep(0.05)
        assert gateway.replica_count == 1

        # SSE streaming through the gateway still works post-drain
        stream_status, stream_text, stream_headers = (
            await loop.run_in_executor(
                None, _post, gateway.port, "/v1/generate",
                {
                    "tokens": [[1, 2, 3, 4]],
                    "max_new_tokens": 8,
                    "stream": True,
                },
            )
        )
        # proxied /v1/model answers from a healthy replica
        model = await loop.run_in_executor(
            None, _get, gateway.port, "/v1/model"
        )
        fleet_view = await loop.run_in_executor(
            None, _get, gateway.port, "/fleet"
        )
        metrics = await loop.run_in_executor(
            None, _get, gateway.port, "/metrics"
        )

        await gateway.stop()
        await member1.stop()
        await member2.stop()
        await replica1.stop()
        await replica2.stop()
        return (
            stream_status, stream_text, stream_headers, model,
            fleet_view, metrics,
        )

    (
        stream_status, stream_text, stream_headers, model,
        fleet_view, metrics,
    ) = run(scenario(), timeout=600)

    assert stream_status == 200
    content_type = {
        k.lower(): v for k, v in stream_headers.items()
    }["content-type"]
    assert "text/event-stream" in content_type
    events = [
        json.loads(line[len("data: "):])
        for line in stream_text.splitlines()
        if line.startswith("data: ")
    ]
    assert events and events[-1].get("done") is True
    streamed = [t for e in events if "tokens" in e for t in e["tokens"]]
    assert len(streamed) == events[-1]["count"] and streamed

    assert model[0] == 200 and "vocab_size" in model[1]
    fleet = json.loads(fleet_view[1])
    assert [r["id"] for r in fleet["replicas"]] == ["replica-2"]
    assert metrics[0] == 200
    # the metrics pipeline recorded the traffic: dispatches to both
    # replicas and the client-visible 200s
    assert 'containerpilot_gateway_routed_total{replica="replica-1"}' in metrics[1]
    assert 'containerpilot_gateway_routed_total{replica="replica-2"}' in metrics[1]
    assert (
        'containerpilot_gateway_requests_total'
        '{code="200",endpoint="generate"}'
    ) in metrics[1]


# -- mux transport through the gateway (stub replicas, no JAX) ----------


def test_mux_hedge_loser_cancelled_not_torn_down(run, tmp_path):
    """PR 8's headline cancel semantics: the losing hedge leg becomes
    a CANCEL frame — counter-pinned — and the slow replica's shared
    connection stays in service for the next request instead of being
    discarded (pre-mux, every hedge loss burned a pooled conn)."""
    backend = FileCatalogBackend(str(tmp_path))

    async def scenario():
        slow, fast = HTTPServer(), HTTPServer()

        async def handler_slow(_req):
            await asyncio.sleep(1.0)
            return Response(200, b'{"who": "slow"}',
                            content_type="application/json")

        async def handler_fast(_req):
            return Response(200, b'{"who": "fast"}',
                            content_type="application/json")

        slow.route("POST", "/v1/generate", handler_slow)
        fast.route("POST", "/v1/generate", handler_fast)
        await slow.start_tcp("127.0.0.1", 0)
        await fast.start_tcp("127.0.0.1", 0)
        _register(backend, "aaa", slow.bound_port)  # tie -> slow first
        _register(backend, "bbb", fast.bound_port)
        gw = FleetGateway(
            backend, "svc", "127.0.0.1", 0,
            poll_interval=5.0, retries=0, hedge_after_ms=80.0,
        )
        await gw.run()
        loop = asyncio.get_event_loop()
        status, text, _ = await loop.run_in_executor(
            None, _post, gw.port, "/v1/generate", {"tokens": [[1]]},
        )
        cancels = _counter(gw._m_mux_cancels, "aaa")  # noqa: SLF001
        saved = _counter(gw._m_conns_saved, "aaa")  # noqa: SLF001
        conns_after_race = slow.connections_accepted
        # the cancelled leg's connection went BACK to service: a
        # follow-up request to the slow replica rides the same socket
        gw._sticky.clear()  # noqa: SLF001
        backend.service_deregister("bbb")
        await gw._poll_once()  # noqa: SLF001
        status2, _text2, _ = await loop.run_in_executor(
            None, _post, gw.port, "/v1/generate", {"tokens": [[1]]},
        )
        conns_after_reuse = slow.connections_accepted
        await gw.stop()
        await slow.stop()
        await fast.stop()
        return (
            status, text, cancels, saved,
            conns_after_race, status2, conns_after_reuse,
        )

    (status, text, cancels, saved, conns_race, status2, conns_reuse) = (
        run(scenario(), timeout=60)
    )
    assert status == 200 and json.loads(text)["who"] == "fast"
    assert cancels == 1 and saved == 1  # the loss was a CANCEL frame
    assert conns_race == 1  # one mux conn carried the losing leg
    assert status2 == 200
    assert conns_reuse == 1  # ...and SURVIVED to carry the next request


def test_dead_mux_conn_fails_streams_once_each_arming_retry(run, tmp_path):
    """A mux connection dying with streams in flight fails each
    exactly once: every request retries to the healthy replica and
    the dead replica saw each body exactly once — no double-dispatch
    of a request the server might have started."""
    backend = FileCatalogBackend(str(tmp_path))

    async def scenario():
        doomed, healthy = HTTPServer(), HTTPServer()
        gate = asyncio.Event()
        hits = {"doomed": 0, "healthy": 0}

        async def handler_doomed(_req):
            hits["doomed"] += 1
            await gate.wait()  # never answers
            return Response(200, b"{}")

        async def handler_healthy(_req):
            hits["healthy"] += 1
            return Response(200, b'{"tokens": [[9]]}',
                            content_type="application/json")

        doomed.route("POST", "/v1/generate", handler_doomed)
        healthy.route("POST", "/v1/generate", handler_healthy)
        await doomed.start_tcp("127.0.0.1", 0)
        await healthy.start_tcp("127.0.0.1", 0)
        _register(backend, "aaa", doomed.bound_port)  # tie -> doomed
        _register(backend, "bbb", healthy.bound_port)
        gw = FleetGateway(
            backend, "svc", "127.0.0.1", 0, poll_interval=5.0,
            hedge=False, retry_backoff=0.01, affinity="none",
        )
        await gw.run()
        loop = asyncio.get_event_loop()
        posts = [
            loop.run_in_executor(
                None, _post, gw.port, "/v1/generate",
                {"tokens": [[1]], "i": i},
            )
            for i in range(2)
        ]
        # both streams in flight on the doomed replica's ONE conn
        for _ in range(200):
            if hits["doomed"] == 2:
                break
            await asyncio.sleep(0.01)
        await doomed.abort()  # SIGKILL semantics: RST, flush nothing
        results = await asyncio.gather(*posts)
        retried = _counter(gw._m_retried, "aaa")  # noqa: SLF001
        await gw.stop()
        await healthy.stop()
        return results, dict(hits), retried

    results, hits, retried = run(scenario(), timeout=60)
    assert [status for status, _t, _h in results] == [200, 200]
    # each stream failed ONCE and was dispatched exactly once to each
    # side: no silent redispatch onto the dead conn, no double-serve
    assert hits == {"doomed": 2, "healthy": 2}
    assert retried == 2


def test_mux_cold_burst_shares_one_dial(run, tmp_path):
    """N concurrent requests against a COLD gateway share one
    upgrade dial: the replica sees a single connection, not a
    stampede of N sockets racing to become the shared conn."""
    backend = FileCatalogBackend(str(tmp_path))

    async def scenario():
        replica = HTTPServer()

        async def handler(_req):
            await asyncio.sleep(0.05)  # keep the burst overlapping
            return Response(200, b"{}", content_type="application/json")

        replica.route("POST", "/v1/generate", handler)
        await replica.start_tcp("127.0.0.1", 0)
        _register(backend, "aaa", replica.bound_port)
        gw = FleetGateway(
            backend, "svc", "127.0.0.1", 0, poll_interval=5.0,
            hedge=False,
        )
        await gw.run()
        loop = asyncio.get_event_loop()
        results = await asyncio.gather(*[
            loop.run_in_executor(
                None, _post, gw.port, "/v1/generate", {"tokens": [[1]]},
            )
            for _ in range(8)
        ])
        conns = replica.connections_accepted
        streams = replica.mux_streams_served
        await gw.stop()
        await replica.stop()
        return [s for s, _t, _h in results], conns, streams

    statuses, conns, streams = run(scenario(), timeout=60)
    assert statuses == [200] * 8
    assert conns == 1  # one shared dial, no cold-start stampede
    assert streams == 8


def test_mux_stale_connection_redialed_transparently(run, tmp_path):
    """A mux connection the replica reaped while idle is replaced
    without the client seeing a failure and WITHOUT consuming a
    routing retry — the mux mirror of the classic pooled
    stale-redial discipline."""
    backend = FileCatalogBackend(str(tmp_path))

    async def scenario():
        replica = HTTPServer()
        replica.KEEPALIVE_IDLE_TIMEOUT = 0.15

        async def handler(_req):
            return Response(200, b"{}", content_type="application/json")

        replica.route("POST", "/v1/generate", handler)
        await replica.start_tcp("127.0.0.1", 0)
        _register(backend, "aaa", replica.bound_port)
        gw = FleetGateway(
            backend, "svc", "127.0.0.1", 0, poll_interval=5.0,
            hedge=False,
        )
        await gw.run()
        loop = asyncio.get_event_loop()
        first, _, _ = await loop.run_in_executor(
            None, _post, gw.port, "/v1/generate", {"tokens": [[1]]},
        )
        await asyncio.sleep(0.6)  # idle-reap the warm mux conn
        second, _, _ = await loop.run_in_executor(
            None, _post, gw.port, "/v1/generate", {"tokens": [[1]]},
        )
        retried = _counter(gw._m_retried, "aaa")  # noqa: SLF001
        mux_conns = replica.mux_connections
        await gw.stop()
        await replica.stop()
        return first, second, retried, mux_conns

    first, second, retried, mux_conns = run(scenario(), timeout=60)
    assert first == 200 and second == 200
    assert retried == 0  # transparent: no routing-level retry consumed
    assert mux_conns == 2  # the reaped conn was replaced by a redial


def test_mux_sse_abandon_cancels_stream_keeps_connection(run, tmp_path):
    """A downstream client abandoning an SSE relay becomes an
    upstream CANCEL frame: the replica's generator cleanup runs, the
    stream id is freed, and the SAME connection serves the next
    request (pre-mux, a stream always burned its close-delimited
    connection)."""
    backend = FileCatalogBackend(str(tmp_path))

    async def scenario():
        replica = HTTPServer()
        cleaned = asyncio.Event()

        async def sse(_req):
            async def gen():
                try:
                    while True:
                        yield b"data: {\"tick\": 1}\n\n"
                        await asyncio.sleep(0.01)
                finally:
                    cleaned.set()

            return StreamingResponse(gen())

        async def buffered(_req):
            return Response(200, b"{}", content_type="application/json")

        replica.route("POST", "/v1/generate", sse)
        replica.route("POST", "/v1/score", buffered)
        await replica.start_tcp("127.0.0.1", 0)
        _register(backend, "aaa", replica.bound_port)
        gw = FleetGateway(
            backend, "svc", "127.0.0.1", 0, poll_interval=5.0,
            hedge=False,
        )
        await gw.run()
        loop = asyncio.get_event_loop()

        def abandoning_client():
            sock = socket.create_connection(
                ("127.0.0.1", gw.port), timeout=10
            )
            body = b'{"tokens": [[1]], "stream": true}'
            sock.sendall(
                b"POST /v1/generate HTTP/1.1\r\nHost: x\r\n"
                b"Content-Type: application/json\r\n"
                b"Content-Length: " + str(len(body)).encode()
                + b"\r\n\r\n" + body
            )
            got = b""
            while b"tick" not in got:
                got += sock.recv(65536)
            sock.close()  # hang up mid-stream
            return got

        got = await loop.run_in_executor(None, abandoning_client)
        await asyncio.wait_for(cleaned.wait(), 10)
        for _ in range(200):  # relay close runs after the disconnect
            if _counter(gw._m_mux_cancels, "aaa") > 0:  # noqa: SLF001
                break
            await asyncio.sleep(0.01)
        cancels = _counter(gw._m_mux_cancels, "aaa")  # noqa: SLF001
        # the shared conn survived the abandon: a buffered request
        # rides the same socket
        status, _t, _h = await loop.run_in_executor(
            None, _post, gw.port, "/v1/score", {"tokens": [[1]]},
        )
        conns = replica.connections_accepted
        await gw.stop()
        await replica.stop()
        return got, cancels, status, conns

    got, cancels, status, conns = run(scenario(), timeout=60)
    assert b"tick" in got
    assert cancels == 1  # the abandon became a CANCEL frame
    assert status == 200
    assert conns == 1  # one connection through stream AND next request


def test_member_drain_cycle_racecheck_clean(run, tmp_path):
    """Run the full control-plane drain/resume cycle with the
    racecheck harness watching the bus: no maintenance-path publish
    may happen while an application lock is held (the dynamic analog
    of cpcheck's CP-LOCKPUB, which PRs must keep true as the drain
    path grows)."""
    from containerpilot_tpu.analysis import RaceCheck
    from containerpilot_tpu.events import (
        EventBus,
        GLOBAL_ENTER_MAINTENANCE,
        GLOBAL_EXIT_MAINTENANCE,
    )

    backend = FileCatalogBackend(str(tmp_path / "catalog"))

    async def scenario():
        rc = RaceCheck()
        bus = rc.wrap_bus(EventBus())
        stub = _StubReplica()
        member = FleetMember(
            stub, backend, "svc", ttl=2, heartbeat_interval=0.05,
            instance_id="r1",
        )
        # instrument the REAL locks the drain path crosses, so the
        # harness actually has something to catch: the discovery
        # FIFO-queue lock (taken on both the loop thread and the
        # catalog pool threads) and the bus's internal lock
        member.service._lock = rc.lock("service-queue")  # noqa: SLF001
        bus._lock = rc.rlock("bus-internal")  # noqa: SLF001
        await member.start()
        member.attach_bus(bus)
        for _ in range(100):
            if backend.instances("svc"):
                break
            await asyncio.sleep(0.02)
        assert backend.instances("svc")

        bus.publish(GLOBAL_ENTER_MAINTENANCE)
        for _ in range(100):
            if stub.draining and not backend.instances("svc"):
                break
            await asyncio.sleep(0.02)
        assert stub.draining and backend.instances("svc") == []

        bus.publish(GLOBAL_EXIT_MAINTENANCE)
        for _ in range(100):
            if not stub.draining and backend.instances("svc"):
                break
            await asyncio.sleep(0.02)
        assert not stub.draining and backend.instances("svc")

        await member.stop()
        rc.unwrap()
        rc.assert_clean()

    run(scenario(), timeout=60)


def test_member_heartbeat_survives_transient_exception(run, tmp_path):
    """An exception thrown synchronously inside one beat (here: the
    server's drain-surface property glitching) must not kill the
    heartbeat task — a dead loop would silently TTL-expire a healthy
    replica out of every gateway's routing set."""

    class _GlitchyReplica:
        """Drain surface whose `draining` property raises a few times."""

        def __init__(self):
            self.ready = True
            self.inflight = 0
            self.port = 4242
            self.glitches = 0

        @property
        def draining(self):
            if self.glitches > 0:
                self.glitches -= 1
                raise RuntimeError("transient state glitch")
            return False

    backend = FileCatalogBackend(str(tmp_path / "catalog"))

    async def scenario():
        stub = _GlitchyReplica()
        member = FleetMember(
            stub, backend, "svc", ttl=2, heartbeat_interval=0.05,
            instance_id="r1",
        )
        await member.start()
        for _ in range(100):
            if backend.instances("svc"):
                break
            await asyncio.sleep(0.02)
        assert backend.instances("svc")

        stub.glitches = 3  # three beats in a row blow up
        await asyncio.sleep(0.3)
        assert stub.glitches == 0  # the loop kept beating through them
        assert member._beat_task is not None  # noqa: SLF001
        assert not member._beat_task.done()  # noqa: SLF001 — loop alive
        assert backend.instances("svc")  # replica never left the catalog
        await member.stop()
        assert backend.instances("svc") == []

    run(scenario(), timeout=60)
