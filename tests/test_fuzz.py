"""Property-based robustness tests: the config pipeline must never
crash with anything but its own typed errors, whatever bytes arrive.
(Strengthens the reference's table-driven validation strategy with
generative coverage.)"""
import json

import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from containerpilot_tpu.config.loader import ConfigError, parse_config  # noqa: E402
from containerpilot_tpu.config.template import (  # noqa: E402
    TemplateError,
    apply_template,
)
from containerpilot_tpu.config.timing import DurationError, parse_duration  # noqa: E402
from containerpilot_tpu.jobs import JobConfig, JobConfigError  # noqa: E402


@settings(max_examples=200, deadline=None)
@given(st.text(max_size=300))
def test_template_never_crashes_unexpectedly(src):
    """Arbitrary text either renders or raises TemplateError."""
    try:
        apply_template(src, {"A": "1", "B": ""})
    except TemplateError:
        pass


@settings(max_examples=200, deadline=None)
@given(st.text(max_size=300))
def test_parse_config_never_crashes_unexpectedly(src):
    try:
        parse_config(src)
    except (ConfigError, TemplateError):
        pass


@settings(max_examples=200, deadline=None)
@given(
    st.one_of(
        st.text(max_size=20),
        st.integers(min_value=-(10 ** 12), max_value=10 ** 12),
        st.floats(allow_nan=False, allow_infinity=False),
        st.none(),
        st.booleans(),
    )
)
def test_parse_duration_total(raw):
    """Any scalar either parses to a float or raises DurationError."""
    try:
        result = parse_duration(raw)
        assert isinstance(result, float)
    except DurationError:
        pass


_JSONISH = st.recursive(
    st.one_of(
        st.none(),
        st.booleans(),
        st.integers(min_value=-(10 ** 9), max_value=10 ** 9),
        st.text(max_size=20),
    ),
    lambda children: st.one_of(
        st.lists(children, max_size=4),
        st.dictionaries(st.text(max_size=10), children, max_size=4),
    ),
    max_leaves=10,
)


@settings(max_examples=200, deadline=None)
@given(
    st.fixed_dictionaries(
        {},
        optional={
            "name": _JSONISH,
            "exec": _JSONISH,
            "port": _JSONISH,
            "restarts": _JSONISH,
            "when": _JSONISH,
            "health": _JSONISH,
            "timeout": _JSONISH,
            "stopTimeout": _JSONISH,
            "logging": _JSONISH,
            "tags": _JSONISH,
            "interfaces": _JSONISH,
        },
    )
)
def test_job_config_never_crashes_unexpectedly(raw):
    """Arbitrary JSON-ish job configs either validate or raise the
    package's typed errors — never an uncontrolled exception."""
    try:
        JobConfig(raw).validate(None)
    except (JobConfigError, ValueError):
        pass  # ValueError covers nested validators (durations, names)


@settings(max_examples=40, deadline=None)
@given(
    n_tokens=st.integers(min_value=0, max_value=5000),
    shard_size=st.integers(min_value=1, max_value=1500),
    seq_len=st.integers(min_value=1, max_value=64),
    batch=st.integers(min_value=1, max_value=8),
    seed=st.integers(min_value=0, max_value=10),
    step=st.integers(min_value=0, max_value=10_000),
)
def test_fuzz_token_shard_dataset(
    tmp_path_factory, n_tokens, shard_size, seq_len, batch, seed, step
):
    """For ANY shard geometry the dataset either raises its typed
    errors or serves deterministic, well-formed, in-range batches."""
    import numpy as np

    from containerpilot_tpu.workload.data import (
        TokenShardDataset,
        write_token_shards,
    )

    directory = str(tmp_path_factory.mktemp("shards"))
    tokens = np.arange(n_tokens, dtype=np.int32) % 97
    write_token_shards(tokens, directory, shard_size=shard_size)
    try:
        ds = TokenShardDataset(
            directory, seq_len, batch, seed=seed, vocab_size=97
        )
    except (FileNotFoundError, ValueError):
        return  # typed rejection of degenerate geometry is correct
    a = ds.batch_at(step)
    b = ds.batch_at(step)
    assert a.shape == (batch, seq_len + 1)
    np.testing.assert_array_equal(a, b)  # pure function of (seed, step)
    assert int(a.min()) >= 0 and int(a.max()) < 97
    # every row is a contiguous slice of the ramp (never crosses shards)
    for row in a:
        deltas = np.diff(row.astype(np.int64)) % 97
        assert (deltas == 1).all()


@settings(max_examples=25, deadline=None)
@given(
    seed=st.integers(0, 2**31 - 1),
    top_k=st.integers(0, 12),
    top_p=st.floats(0.0, 1.0),
    temperature=st.floats(-1.0, 3.0),
)
def test_fuzz_sample_logits_invariants(seed, top_k, top_p, temperature):
    """For any knob combination: the sampled id is in-vocab; a top-k
    filter never yields an id ranked below the k-th logit (ties
    allowed); temperature <= 0 is exactly argmax."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from containerpilot_tpu.models.decode import sample_logits

    vocab = 12
    key = jax.random.PRNGKey(seed)
    logits = jax.random.normal(key, (3, vocab), jnp.float32) * 3.0
    toks = np.asarray(
        sample_logits(
            logits, jax.random.PRNGKey(seed + 1),
            jnp.float32(temperature),
            top_k=jnp.int32(top_k), top_p=jnp.float32(top_p),
        )
    )
    assert ((toks >= 0) & (toks < vocab)).all()
    l_np = np.asarray(logits)
    if temperature <= 0.0:
        np.testing.assert_array_equal(toks, l_np.argmax(-1))
    elif top_k > 0:
        for row, tok in zip(l_np, toks):
            kth = np.sort(row)[::-1][min(top_k, vocab) - 1]
            assert row[tok] >= kth

