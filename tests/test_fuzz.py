"""Property-based robustness tests: the config pipeline must never
crash with anything but its own typed errors, whatever bytes arrive.
(Strengthens the reference's table-driven validation strategy with
generative coverage.)"""
import json

import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from containerpilot_tpu.config.loader import ConfigError, parse_config  # noqa: E402
from containerpilot_tpu.config.template import (  # noqa: E402
    TemplateError,
    apply_template,
)
from containerpilot_tpu.config.timing import DurationError, parse_duration  # noqa: E402
from containerpilot_tpu.jobs import JobConfig, JobConfigError  # noqa: E402


@settings(max_examples=200, deadline=None)
@given(st.text(max_size=300))
def test_template_never_crashes_unexpectedly(src):
    """Arbitrary text either renders or raises TemplateError."""
    try:
        apply_template(src, {"A": "1", "B": ""})
    except TemplateError:
        pass


@settings(max_examples=200, deadline=None)
@given(st.text(max_size=300))
def test_parse_config_never_crashes_unexpectedly(src):
    try:
        parse_config(src)
    except (ConfigError, TemplateError):
        pass


@settings(max_examples=200, deadline=None)
@given(
    st.one_of(
        st.text(max_size=20),
        st.integers(min_value=-(10 ** 12), max_value=10 ** 12),
        st.floats(allow_nan=False, allow_infinity=False),
        st.none(),
        st.booleans(),
    )
)
def test_parse_duration_total(raw):
    """Any scalar either parses to a float or raises DurationError."""
    try:
        result = parse_duration(raw)
        assert isinstance(result, float)
    except DurationError:
        pass


_JSONISH = st.recursive(
    st.one_of(
        st.none(),
        st.booleans(),
        st.integers(min_value=-(10 ** 9), max_value=10 ** 9),
        st.text(max_size=20),
    ),
    lambda children: st.one_of(
        st.lists(children, max_size=4),
        st.dictionaries(st.text(max_size=10), children, max_size=4),
    ),
    max_leaves=10,
)


@settings(max_examples=200, deadline=None)
@given(
    st.fixed_dictionaries(
        {},
        optional={
            "name": _JSONISH,
            "exec": _JSONISH,
            "port": _JSONISH,
            "restarts": _JSONISH,
            "when": _JSONISH,
            "health": _JSONISH,
            "timeout": _JSONISH,
            "stopTimeout": _JSONISH,
            "logging": _JSONISH,
            "tags": _JSONISH,
            "interfaces": _JSONISH,
        },
    )
)
def test_job_config_never_crashes_unexpectedly(raw):
    """Arbitrary JSON-ish job configs either validate or raise the
    package's typed errors — never an uncontrolled exception."""
    try:
        JobConfig(raw).validate(None)
    except (JobConfigError, ValueError):
        pass  # ValueError covers nested validators (durations, names)
