"""Every shipped example config must render, parse, and validate
(golden-fixture discipline; reference: jobs/testdata/* convention)."""
import glob
import os

import pytest

from containerpilot_tpu.config.loader import new_config, parse_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = sorted(glob.glob(os.path.join(REPO, "examples", "*.json5")))


@pytest.mark.parametrize("path", EXAMPLES, ids=os.path.basename)
def test_example_validates(path, tmp_path, monkeypatch):
    monkeypatch.setenv("CATALOG_DIR", str(tmp_path / "catalog"))
    monkeypatch.setenv("CATALOG", f"file:{tmp_path / 'catalog'}")
    with open(path, encoding="utf-8") as f:
        cfg = new_config(parse_config(f.read()))
    assert cfg.jobs, f"{path} defines no jobs"


def test_examples_exist():
    assert len(EXAMPLES) >= 5
