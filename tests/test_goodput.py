"""Device-time ledger tests (telemetry/goodput.py + its wiring).

Pure-ledger units run with synthetic clocks (no JAX, exact math);
the engine/server/gateway tests boot the real tiny-model stack on
the CPU backend and prove the shipped wiring: every wall-second
attributed (sums to uptime), warmup compile stamped before /health
flips 200, the hotpath no-per-token contract, the gp= heartbeat
note with torn-note merge, departed-replica fold-in, scale-event
time-to-first-routed-token, and the /v1/goodput + /fleet schemas.
"""
import asyncio
import json
import time
import urllib.error
import urllib.request

import pytest

from containerpilot_tpu.discovery import (
    FileCatalogBackend,
    NoopBackend,
)
from containerpilot_tpu.telemetry import goodput
from containerpilot_tpu.telemetry.goodput import (
    DeviceTimeLedger,
    NOTE_FIELDS,
    STAGES,
    find_scheduling_gaps,
    merge_note_max,
    parse_note,
    productive_fraction,
    sum_stage_totals,
)


def _get(port, path, timeout=30):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read().decode(), dict(resp.headers)
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read().decode(), dict(exc.headers)


def _post(port, path, payload, timeout=120):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read().decode(), dict(resp.headers)
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read().decode(), dict(exc.headers)


# -- the state machine (synthetic clock, exact math) --------------------


def test_ledger_transitions_sum_to_wall_time():
    """Every second between t0 and now lands in exactly one stage:
    the per-stage totals sum to wall time EXACTLY (the 2% acceptance
    tolerance is for cross-surface scrape skew, not the ledger)."""
    led = DeviceTimeLedger(now=100.0)
    led.enter("compile_warmup", now=101.5)
    led.enter("idle", now=104.0)
    led.enter("prefill", now=104.5)
    led.enter("decode", now=105.25)
    led.engine_idle(now=107.0)
    totals = led.totals(now=110.0)
    assert sum(totals.values()) == pytest.approx(10.0, abs=1e-9)
    assert totals["boot"] == pytest.approx(1.5)
    assert totals["compile_warmup"] == pytest.approx(2.5)
    assert totals["prefill"] == pytest.approx(0.75)
    assert totals["decode"] == pytest.approx(1.75)
    assert totals["idle"] == pytest.approx(3.5)  # 0.5 + 3.0 open
    snap = led.snapshot(now=110.0)
    assert snap["uptime_s"] == pytest.approx(10.0)
    assert sum(snap["stages_s"].values()) == pytest.approx(
        snap["uptime_s"], abs=0.01
    )
    assert set(snap["stages_s"]) == set(STAGES)


def test_ledger_engine_idle_cannot_cut_boot_short():
    """The engine worker blocks on its queue the moment it starts —
    long before warmup. engine_idle only flips OUT of an engine
    stage, so boot/compile attribution survives."""
    led = DeviceTimeLedger(now=0.0)
    led.engine_idle(now=1.0)  # worker blocked during boot: no-op
    assert led.totals(now=2.0)["boot"] == pytest.approx(2.0)
    led.enter("prefill", now=2.0)
    led.enter("decode", now=3.0)
    led.engine_idle(now=4.0)  # real transition
    totals = led.totals(now=5.0)
    assert totals["idle"] == pytest.approx(1.0)
    assert totals["decode"] == pytest.approx(1.0)


def test_ledger_override_owns_attribution():
    """Warmup/drain overrides: the engine's stamps keep moving the
    underlying stage, but every second is attributed to the override
    until it clears — a warmup dummy request's compile lands in
    compile_warmup, a draining replica's last decodes in drain."""
    led = DeviceTimeLedger(now=0.0)
    led.set_override("compile_warmup", now=1.0)
    led.enter("prefill", now=2.0)  # warmup's dummy admission
    led.enter("decode", now=3.0)
    led.engine_idle(now=4.0)
    led.clear_override(now=5.0)
    totals = led.totals(now=5.0)
    assert totals["boot"] == pytest.approx(1.0)
    assert totals["compile_warmup"] == pytest.approx(4.0)
    assert totals["prefill"] == totals["decode"] == 0.0
    # post-clear, the underlying stage (idle) accrues again
    assert led.totals(now=7.0)["idle"] == pytest.approx(2.0)
    # first_productive_at is NOT stamped under an override (warmup's
    # dummy prefill is not routed traffic)
    assert led.first_productive_at is None
    led.enter("prefill", now=8.0)
    assert led.first_productive_at == 8.0
    # drain override
    led.set_override("drain", now=9.0)
    led.enter("decode", now=9.5)
    led.clear_override(now=11.0)
    assert led.totals(now=11.0)["drain"] == pytest.approx(2.0)


def test_ledger_kv_carve_clamps_to_open_segment():
    """The kv_readmit carve re-attributes readmit seconds out of the
    running prefill segment, clamped so totals never exceed wall."""
    led = DeviceTimeLedger(now=0.0)
    led.enter("prefill", now=1.0)
    led.carve("kv_readmit", 0.3, now=1.5)
    led.enter("decode", now=2.0)
    totals = led.totals(now=2.0)
    assert totals["kv_readmit"] == pytest.approx(0.3)
    assert totals["prefill"] == pytest.approx(0.7)
    # a carve exceeding the open segment clamps (never negative
    # prefill, never attributed seconds > wall seconds)
    led2 = DeviceTimeLedger(now=0.0)
    led2.enter("prefill", now=1.0)
    led2.carve("kv_readmit", 99.0, now=1.4)
    totals2 = led2.totals(now=1.4)
    assert totals2["kv_readmit"] == pytest.approx(0.4)
    assert sum(totals2.values()) == pytest.approx(1.4)


def test_ledger_freeze_stops_the_clock():
    """A stopped/killed replica's ledger freezes — reads afterwards
    see the totals as of death (in production the process's note
    simply stops updating; in-process harnesses must match)."""
    led = DeviceTimeLedger(now=0.0)
    led.enter("idle", now=1.0)
    led.freeze(now=3.0)
    assert sum(led.totals(now=50.0).values()) == pytest.approx(3.0)
    assert led.snapshot(now=50.0)["uptime_s"] == pytest.approx(3.0)
    # WRITES after the freeze clamp too: stop()/abort() freezes the
    # ledger while the engine worker may still stamp its in-flight
    # round's boundaries — a late enter/engine_idle/carve must not
    # accrue past death or totals exceed the frozen uptime
    led.enter("decode", now=10.0)
    led.engine_idle(now=20.0)
    led.carve("kv_readmit", 5.0, now=30.0)
    led.clear_override(now=40.0)
    assert sum(led.totals(now=50.0).values()) == pytest.approx(3.0)
    assert led.totals(now=50.0)["decode"] == 0.0
    assert led.totals(now=50.0)["kv_readmit"] == 0.0


def test_ledger_rejects_unknown_stage():
    led = DeviceTimeLedger(now=0.0)
    with pytest.raises(ValueError):
        led.enter("lunch")
    with pytest.raises(ValueError):
        led.set_override("lunch")
    with pytest.raises(ValueError):
        led.carve("lunch", 1.0)


# -- wire format --------------------------------------------------------


def test_note_roundtrip_and_torn_note_merge():
    led = DeviceTimeLedger(now=0.0)
    led.enter("compile_warmup", now=2.0)
    led.enter("idle", now=5.0)
    note = led.note(dispatches=12, tokens_out=340, now=6.0)
    assert "=" not in note  # value-only: fleet/notes.py owns gp=
    parsed = parse_note(note)
    assert parsed["boot"] == pytest.approx(2.0)
    assert parsed["compile_warmup"] == pytest.approx(3.0)
    assert parsed["idle"] == pytest.approx(1.0)
    assert parsed["dispatches"] == 12
    assert parsed["tokens_out"] == 340
    # a torn note (truncated mid-field) parses its good prefix and
    # zero-fills the tail — never throws on the poll path
    torn = parse_note("2.000,3.0")
    assert torn["boot"] == pytest.approx(2.0)
    assert torn["compile_warmup"] == pytest.approx(3.0)
    assert torn["idle"] == 0.0
    # garbage and non-strings are harmless
    assert parse_note("abc")["boot"] == 0.0
    assert parse_note(None)["boot"] == 0.0
    assert parse_note("1.0,nan,5.0")["compile_warmup"] == 0.0
    assert parse_note("1.0,inf")["compile_warmup"] == 0.0
    # elementwise max: cumulative fields never regress through a torn
    # read — the kv= counters' discipline, applied to seconds
    merged = merge_note_max(parsed, torn)
    assert merged["idle"] == pytest.approx(1.0)  # kept from prev
    assert merged["boot"] == pytest.approx(2.0)
    assert set(merged) == set(NOTE_FIELDS)


def test_fleet_summation_and_productive_fraction():
    a = {"boot": 1.0, "idle": 2.0, "prefill": 1.0, "decode": 2.0,
         "dispatches": 10, "tokens_out": 100}
    b = {"compile_warmup": 4.0, "decode": 2.0, "dispatches": 30,
         "tokens_out": 60}
    totals = sum_stage_totals([a, b])
    assert totals["decode"] == pytest.approx(4.0)
    assert totals["dispatches"] == 40
    assert productive_fraction(totals) == pytest.approx(
        5.0 / 12.0, abs=1e-3
    )
    assert productive_fraction({}) is None
    summary = goodput.fleet_summary([a, b])
    assert summary["dispatches_per_token"] == pytest.approx(0.25)
    assert summary["device_seconds"] == pytest.approx(12.0)
    assert set(summary["stages_s"]) == set(STAGES)


# -- scheduling-gap detection -------------------------------------------


def test_scheduling_gap_flags_queue_wait_over_idle():
    """slot_queue_wait dominant + ledger idle inside the same window
    = a scheduling gap (capacity sat free while the request queued);
    a queue wait with NO idle overlap (genuinely busy fleet) is not
    flagged."""
    from containerpilot_tpu.telemetry.tracing import TraceRecorder

    rec = TraceRecorder("replica")
    queued = rec.start(endpoint="generate")
    queued.add_span("slot_queue_wait", 100.0, 101.0)
    queued.add_span("decode", 101.0, 101.1)
    busy = rec.start(endpoint="generate")
    busy.add_span("slot_queue_wait", 200.0, 201.0)
    busy.add_span("decode", 201.0, 201.1)
    fast = rec.start(endpoint="generate")
    fast.add_span("decode", 300.0, 301.0)  # decode-dominant: skip
    idle_spans = [(100.4, 100.9), (150.0, 160.0)]
    gaps = find_scheduling_gaps(
        [queued, busy, fast], idle_spans, min_overlap_s=0.005
    )
    assert len(gaps) == 1
    assert gaps[0]["trace_id"] == queued.trace_id
    assert gaps[0]["idle_overlap_ms"] == pytest.approx(500.0, abs=1.0)
    assert gaps[0]["slot_queue_wait_ms"] == pytest.approx(
        1000.0, abs=1.0
    )
    # no idle spans at all -> nothing to flag, cheaply
    assert find_scheduling_gaps([queued], []) == []


# -- gateway aggregation units (no servers, no JAX) ---------------------


def test_gateway_applies_gp_notes_with_torn_note_discipline():
    from containerpilot_tpu.fleet import FleetGateway
    from containerpilot_tpu.fleet.gateway import Replica

    gw = FleetGateway(NoopBackend(), "svc")
    replica = Replica("r1", "h", 1)
    gw._apply_notes(
        replica, "ok occ=0.50 gp=1.000,4.000,2.000,0.500,1.500,"
        "0.000,0.000,20,200"
    )
    assert replica.goodput["compile_warmup"] == pytest.approx(4.0)
    assert replica.goodput["tokens_out"] == 200
    # a torn re-read must not regress any cumulative field
    gw._apply_notes(replica, "ok gp=1.500,2")
    assert replica.goodput["boot"] == pytest.approx(1.5)
    assert replica.goodput["compile_warmup"] == pytest.approx(4.0)
    assert replica.goodput["tokens_out"] == 200
    gw._replicas = {"r1": replica}
    blob = gw.fleet_goodput()
    assert blob["stages_s"]["compile_warmup"] == pytest.approx(4.0)
    assert blob["productive_fraction"] == pytest.approx(
        2.0 / 9.5, abs=1e-3
    )
    assert blob["dispatches_per_token"] == pytest.approx(0.1)
    assert "r1" in blob["replicas"]


def test_gateway_folds_departed_replicas_into_fleet_ledger():
    from containerpilot_tpu.fleet import FleetGateway
    from containerpilot_tpu.fleet.gateway import Replica

    gw = FleetGateway(NoopBackend(), "svc")
    gone = Replica("r-gone", "h", 1)
    gw._apply_notes(gone, "ok gp=1.000,5.000,1.000,1.000,2.000,0,0,5,50")
    live = Replica("r-live", "h", 2)
    gw._apply_notes(live, "ok gp=0.500,0.500,1.000,0.000,1.000,0,0,2,20")
    # simulate the poll-time departure fold-in
    gw._goodput_departed["r-gone"] = dict(gone.goodput)
    gw._replicas = {"r-live": live}
    blob = gw.fleet_goodput()
    assert blob["stages_s"]["compile_warmup"] == pytest.approx(5.5)
    assert blob["tokens_out"] == 70
    assert "r-gone" in blob["departed"]
    assert blob["departed"]["r-gone"]["stages_s"]["decode"] == (
        pytest.approx(2.0)
    )
    # a flapped-out id that REJOINS reclaims its parked entry (the
    # rejoin path pops it, so the cumulative note isn't double
    # counted) — mirror of the tokens_reused discipline
    gw._goodput_departed.pop("r-gone", None)
    gw._replicas["r-gone"] = gone
    blob2 = gw.fleet_goodput()
    assert blob2["stages_s"]["compile_warmup"] == pytest.approx(5.5)


def test_gateway_scale_event_ttfrt_computation():
    """TTFRT = first 200 served by the launched replica minus the
    launch decision stamp; None until the replica actually serves."""
    from containerpilot_tpu.fleet import FleetGateway

    class _Scaler:
        scale_log = [
            {"direction": "up", "replica": "r-new", "at": 100.0},
            {"direction": "up", "replica": "r-cold", "at": 200.0},
            {"direction": "down", "replica": "r-old", "at": 300.0},
        ]
        stats = {}

    gw = FleetGateway(NoopBackend(), "svc")
    gw.attach_autoscaler(_Scaler())
    gw._first_ok["r-new"] = 104.5
    events = gw.scale_event_report()
    assert events[0] == {
        "direction": "up", "replica": "r-new", "ttfrt_s": 4.5,
    }
    assert events[1]["ttfrt_s"] is None  # launched, never served
    assert "ttfrt_s" not in events[2]  # downs carry no TTFRT
    # the /fleet blob carries the same events
    assert gw.fleet_goodput()["scale_events"] == events


def test_gateway_first_ok_stamp_is_first_only():
    from containerpilot_tpu.fleet import FleetGateway
    from containerpilot_tpu.fleet.gateway import Replica

    gw = FleetGateway(NoopBackend(), "svc")
    replica = Replica("r1", "h", 1)
    gw._stamp_first_ok(replica)
    first = replica.first_ok_at
    assert first is not None
    assert gw._first_ok["r1"] == first
    time.sleep(0.01)
    gw._stamp_first_ok(replica)
    assert replica.first_ok_at == first  # first stamp wins
    assert gw._first_ok["r1"] == first


# -- the engine contract (tiny model, CPU) ------------------------------


def _tiny_model(max_len=64):
    import jax
    import jax.numpy as jnp

    from containerpilot_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=max_len, dtype=jnp.float32,
    )
    return cfg, init_params(jax.random.PRNGKey(0), cfg)


def test_engine_ledger_stamps_are_bounded_not_per_token():
    """The hotpath contract, ledger edition (mirror of the PR 9
    engine-timings test): however many tokens a request decodes, the
    engine's ledger transitions are a small constant per request —
    and dispatches/token stays well under 1 (chunked decode)."""
    from containerpilot_tpu.workload.serve_slots import SlotEngine

    cfg, params = _tiny_model(max_len=128)
    led = DeviceTimeLedger()
    engine = SlotEngine(
        cfg, params, 128, slots=2, chunk=8, ledger=led
    )
    try:
        engine.submit([1, 2, 3, 4], max_new=2).result(timeout=120)
        before = led.transitions
        tokens_before = engine.tokens_out
        engine.submit([1, 2, 3, 4], max_new=96).result(timeout=120)
        decoded = engine.tokens_out - tokens_before
        assert decoded >= 90
        # one request = enter(prefill) + enter(decode) + engine_idle
        # (+ slack for scheduling variance): O(1), never O(tokens)
        assert led.transitions - before <= 8
        assert engine.dispatches / engine.tokens_out < 0.5
        totals = led.totals()
        assert totals["prefill"] > 0.0
        assert totals["decode"] > 0.0
    finally:
        engine.stop()


def test_server_goodput_surface_and_accounting(run):
    """The shipped replica wiring end to end: /v1/goodput sums to
    uptime within 2%, compile_warmup was stamped BEFORE /health
    flipped 200 (no idle-attributed boot lie), /metrics carries
    cp_device_seconds_total{stage} + the dispatch counters, the
    heartbeat note parses, and drain seconds attribute."""
    from containerpilot_tpu.workload.serve import InferenceServer

    cfg, params = _tiny_model()
    server = InferenceServer(
        cfg, params, "127.0.0.1", 0, max_len=64, slots=2, slot_chunk=4
    )

    async def scenario():
        loop = asyncio.get_event_loop()
        await server.run()
        # ready flipped: warmup compile must ALREADY be attributed
        snap = server.ledger.snapshot()
        assert snap["stages_s"]["compile_warmup"] > 0.0
        assert snap["stage"] in ("idle", "prefill", "decode")
        status, body, _ = await loop.run_in_executor(
            None, _post, server.port, "/v1/generate",
            {"tokens": [[1, 2, 3, 4]], "max_new_tokens": 8},
        )
        assert status == 200
        status, body, _ = await loop.run_in_executor(
            None, _get, server.port, "/v1/goodput"
        )
        assert status == 200
        gp = json.loads(body)
        assert gp["role"] == "replica"
        assert set(gp["stages_s"]) == set(STAGES)
        attributed = sum(gp["stages_s"].values())
        assert attributed == pytest.approx(
            gp["uptime_s"], rel=0.02, abs=0.02
        )
        assert gp["stages_s"]["prefill"] > 0.0
        assert gp["productive_fraction"] > 0.0
        assert gp["tokens_out"] >= 8
        assert gp["dispatches_per_token"] is not None
        assert isinstance(gp["scheduling_gaps"], list)
        # metrics face
        status, metrics, _ = await loop.run_in_executor(
            None, _get, server.port, "/metrics"
        )
        for stage in STAGES:
            assert f'cp_device_seconds_total{{stage="{stage}"}}' in (
                metrics
            )
        assert "cp_decode_dispatches_total" in metrics
        assert "cp_tokens_out_total" in metrics
        # heartbeat note face (value-only: fleet/notes.py owns gp=)
        note = server.goodput_note()
        parsed = parse_note(note)
        assert parsed["compile_warmup"] > 0.0
        assert parsed["tokens_out"] >= 8
        # drain attribution
        server.enter_maintenance()
        await asyncio.sleep(0.05)
        assert server.ledger.stage == "drain"
        server.exit_maintenance()
        drained = server.ledger.totals()["drain"]
        assert drained > 0.0
        await server.stop()
        # stop froze the ledger
        final = sum(server.ledger.totals().values())
        await asyncio.sleep(0.05)
        assert sum(server.ledger.totals().values()) == pytest.approx(
            final
        )

    run(scenario(), timeout=120)


def test_member_heartbeat_carries_gp_note(run, tmp_path):
    """A FleetMember's TTL beat appends the duck-typed goodput_note
    the way kv_note rides — and the catalog notes round-trip it."""
    from containerpilot_tpu.fleet import FleetMember

    backend = FileCatalogBackend(str(tmp_path))

    class _Stub:
        ready = True
        draining = False
        inflight = 0
        port = 4242
        occupancy = 0.5

        def goodput_note(self):
            return "1.000,2.000,3.000,0.100,0.200,0.000,0.000,4,40"

    async def scenario():
        member = FleetMember(
            _Stub(), backend, "svc", ttl=5,
            heartbeat_interval=0.05, instance_id="r1",
        )
        await member.start()
        note = ""
        for _ in range(200):
            instances = backend.instances("svc")
            if instances and "gp=" in (instances[0].notes or ""):
                note = instances[0].notes
                break
            await asyncio.sleep(0.02)
        await member.stop()
        assert "gp=" in note
        from containerpilot_tpu.kvtier import parse_kv_note

        fields = parse_kv_note(note)
        parsed = parse_note(fields["gp"])
        assert parsed["idle"] == pytest.approx(3.0)
        assert parsed["tokens_out"] == 40

    run(scenario(), timeout=60)


def test_fleet_goodput_schema_consistent_with_replica_ledgers(
    run, tmp_path
):
    """Live 2-replica acceptance: the gateway's /fleet goodput block
    (built from heartbeat notes alone) must agree with the replicas'
    own /v1/goodput ledgers — same stages, fleet seconds within the
    heartbeat-staleness window, productive_fraction consistent."""
    from containerpilot_tpu.fleet import FleetGateway, FleetMember
    from containerpilot_tpu.workload.serve import InferenceServer

    backend = FileCatalogBackend(str(tmp_path / "catalog"))
    cfg, params = _tiny_model()

    async def scenario():
        loop = asyncio.get_event_loop()
        servers, members = [], []
        for i in range(2):
            server = InferenceServer(
                cfg, params, "127.0.0.1", 0, max_len=64,
                slots=2, slot_chunk=4,
            )
            await server.run()
            member = FleetMember(
                server, backend, "svc", ttl=5,
                heartbeat_interval=0.05, instance_id=f"r{i}",
            )
            await member.start()
            servers.append(server)
            members.append(member)
        gw = FleetGateway(
            backend, "svc", "127.0.0.1", 0, poll_interval=0.1,
        )
        await gw.run()
        for _ in range(100):
            if gw.replica_count == 2:
                break
            await asyncio.sleep(0.05)
        assert gw.replica_count == 2
        for i in range(4):
            status, _, _ = await loop.run_in_executor(
                None, _post, gw.port, "/v1/generate",
                {"tokens": [[1, 2, 3, i + 1]], "max_new_tokens": 6},
            )
            assert status == 200
        # let a post-traffic heartbeat ship fresh totals
        await asyncio.sleep(0.3)
        status, body, _ = await loop.run_in_executor(
            None, _get, gw.port, "/fleet"
        )
        fleet = json.loads(body)["goodput"]
        status, body, _ = await loop.run_in_executor(
            None, _get, gw.port, "/v1/goodput"
        )
        standalone = json.loads(body)
        assert set(fleet["stages_s"]) == set(STAGES)
        assert set(fleet["replicas"]) == {"r0", "r1"}
        assert fleet["scale_events"] == []
        assert standalone["stages_s"].keys() == fleet["stages_s"].keys()
        # consistency with the replicas' own ledgers: the notes lag
        # by at most a heartbeat + poll, so compare with that slack
        direct = [s.ledger.totals() for s in servers]
        fleet_total = sum(fleet["stages_s"].values())
        direct_total = sum(
            sum(t.values()) for t in direct
        )
        assert fleet_total == pytest.approx(
            direct_total, rel=0.25, abs=1.5
        )
        # productive_fraction consistent with the per-replica ledgers
        merged = sum_stage_totals(direct)
        expect = productive_fraction(merged)
        if fleet["productive_fraction"] and expect:
            assert fleet["productive_fraction"] == pytest.approx(
                expect, rel=0.5, abs=0.02
            )
        for member in members:
            await member.stop()
        await gw.stop()
        for server in servers:
            await server.stop()

    run(scenario(), timeout=180)
