"""Cross-hop tracing units (telemetry/tracing.py): ring retention,
contextvar isolation, the digest wire format, dominant-stage
attribution, logger correlation, and the build-info gauge."""
import asyncio
import http.client
import json
import logging

import pytest

from containerpilot_tpu.config.logger import LogConfig
from containerpilot_tpu.telemetry import tracing
from containerpilot_tpu.utils.http import HTTPServer, Request, Response
from containerpilot_tpu.utils.httpclient import keepalive_request
from containerpilot_tpu.utils.prom import ensure_build_info


# -- recorder retention -------------------------------------------------


def test_recent_ring_evicts_oldest():
    rec = tracing.TraceRecorder("t", recent=3, slowest=2)
    ids = []
    for _ in range(5):
        trace = rec.start(endpoint="e")
        ids.append(trace.trace_id)
        trace.finish(200)
    assert rec.recorded == 5
    kept = [t.trace_id for t in rec.recent()]
    # newest first, capped at 3, the two oldest evicted
    assert kept == ids[-1:-4:-1]


def test_slowest_board_keeps_the_slow_ones():
    rec = tracing.TraceRecorder("t", recent=2, slowest=2)
    durations = {}
    for ms in (5, 50, 1, 20):
        trace = rec.start(endpoint="e")
        # synthetic duration: rewind the start stamp
        trace.started -= ms / 1e3
        trace.finish(200)
        durations[trace.trace_id] = ms
    slow = [durations[t.trace_id] for t in rec.slowest()]
    assert slow == [50, 20]  # slowest first; 5 and 1 fell off
    # the ring, meanwhile, is purely most-recent
    assert [durations[t.trace_id] for t in rec.recent()] == [20, 1]


def test_finish_is_idempotent_and_records_once():
    rec = tracing.TraceRecorder("t")
    trace = rec.start(endpoint="e")
    trace.finish(429)
    trace.finish(200)
    assert rec.recorded == 1
    assert rec.recent()[0].status == 429  # first finish wins
    assert rec.find(trace.trace_id)


def test_refused_trace_is_findable_with_zero_spans():
    """A shed (429/504) dispatched nothing — its trace still lands in
    the ring so a client-reported failure is findable by id."""
    rec = tracing.TraceRecorder("gateway")
    trace = rec.start(trace_id="cafe0123cafe0123", endpoint="generate")
    trace.finish(429)
    found = rec.find("cafe0123cafe0123")
    assert found and found[0].spans == []


# -- spans + context ----------------------------------------------------


def test_span_cap_bounds_memory():
    rec = tracing.TraceRecorder("t")
    trace = rec.start(endpoint="e")
    for i in range(tracing.MAX_SPANS * 2):
        trace.add_span("s", 0.0, 1.0)
    assert len(trace.spans) == tracing.MAX_SPANS


def test_contextvar_isolation_across_concurrent_tasks(run):
    """Two concurrent tasks, two traces: spans recorded through the
    module-level ``span()`` land on each task's own trace — task
    creation snapshots the context, so there is no bleed."""
    rec = tracing.TraceRecorder("t")

    async def worker(name: str, trace: tracing.Trace):
        token = tracing.activate(trace)
        try:
            assert tracing.current_trace_id() == trace.trace_id
            with tracing.span(f"stage_{name}"):
                await asyncio.sleep(0.01)
            with tracing.span(f"stage_{name}_2"):
                await asyncio.sleep(0.005)
        finally:
            tracing.deactivate(token)

    async def scenario():
        t_a, t_b = rec.start(endpoint="a"), rec.start(endpoint="b")

        async def spawn(name, trace):
            # ensure_future copies the ambient context; activation
            # happens INSIDE the task so each binds only its own
            return asyncio.ensure_future(worker(name, trace))

        await asyncio.gather(
            await spawn("a", t_a), await spawn("b", t_b)
        )
        return t_a, t_b

    t_a, t_b = run(scenario())
    assert {s[0] for s in t_a.spans} == {"stage_a", "stage_a_2"}
    assert {s[0] for s in t_b.spans} == {"stage_b", "stage_b_2"}


def test_module_span_is_noop_without_active_trace():
    with tracing.span("anything"):
        pass  # must not raise, must not record anywhere


def test_cancelled_span_records_nothing(run):
    """A hedge's losing leg (or an abandoned client's task) exits its
    upstream spans via CancelledError: recording those would misalign
    the digest-stitch anchor and double-count the stage in dominance,
    so a cancelled span must vanish. A span exiting via a REAL
    failure still records — time spent failing is signal."""
    rec = tracing.TraceRecorder("t")
    trace = rec.start(endpoint="e")

    async def loser():
        with tracing.span("upstream_ttfb"):
            await asyncio.sleep(30)

    async def scenario():
        token = tracing.activate(trace)
        try:
            task = asyncio.ensure_future(loser())
            await asyncio.sleep(0.01)
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
        finally:
            tracing.deactivate(token)

    run(scenario())
    assert trace.spans == []
    with pytest.raises(RuntimeError):
        with trace.span("upstream_ttfb"):
            raise RuntimeError("upstream died")
    assert [s[0] for s in trace.spans] == ["upstream_ttfb"]


def test_safe_id_rejects_splice_hostile_ids():
    """Peer-supplied trace ids ride unescaped into the cached mux
    HEADERS template and echoed answer headers — adoption points must
    filter through safe_id."""
    assert tracing.safe_id("cafe0123cafe0123") == "cafe0123cafe0123"
    assert tracing.safe_id("client-Req_42") == "client-Req_42"
    for hostile in (
        None, "", "a" * (tracing.MAX_ID_LEN + 1),
        'a"},"path":"/v1/score', "id with spaces", "id\r\nInjected: 1",
        "id;semi", "id~tilde",
    ):
        assert tracing.safe_id(hostile) is None


def test_snapshot_json_shared_handler_body():
    rec = tracing.TraceRecorder("t")
    for _ in range(3):
        rec.start(endpoint="e").finish(200)
    body = json.loads(rec.snapshot_json({}))
    assert len(body["recent"]) == 3
    bounded = json.loads(rec.snapshot_json({"n": ["1"]}))
    assert len(bounded["recent"]) == 1
    ignored = json.loads(rec.snapshot_json({"n": ["-5x"]}))
    assert len(ignored["recent"]) == 3  # non-numeric ?n= ignored


# -- digest wire format -------------------------------------------------


def test_digest_roundtrip():
    rec = tracing.TraceRecorder("replica")
    trace = rec.start(endpoint="generate")
    base = trace.started
    trace.add_span("prefill", base + 0.001, base + 0.004)
    trace.add_span("decode", base + 0.004, base + 0.050, rounds=7)
    digest = trace.digest()
    parsed = tracing.parse_digest(digest)
    assert [p[0] for p in parsed] == ["prefill", "decode"]
    assert abs(parsed[0][1] - 0.001) < 1e-4  # offset survives
    assert abs(parsed[1][2] - 0.046) < 1e-4  # duration survives


def test_parse_digest_tolerates_garbage():
    assert tracing.parse_digest("") == []
    assert tracing.parse_digest("no-tildes-here") == []
    assert tracing.parse_digest("a~x~y;b~1.0~2.0;~3~4") == [
        ("b", 0.001, 0.002)
    ]
    # a hostile peer cannot balloon memory through the digest
    flood = ";".join("s~1~1" for _ in range(10_000))
    assert len(tracing.parse_digest(flood)) == tracing.MAX_DIGEST_SPANS


def test_child_digest_is_spliced_with_prefix_and_alignment():
    rec = tracing.TraceRecorder("gateway")
    trace = rec.start(endpoint="generate")
    dispatch_at = trace.started + 0.010
    trace.add_span("upstream_ttfb", dispatch_at, dispatch_at + 0.100)
    trace.add_child_digest("prefill~2.000~5.000", base=dispatch_at)
    stage, start, end, _meta = trace.spans[-1]
    assert stage == "replica.prefill"
    assert abs(start - (dispatch_at + 0.002)) < 1e-6
    assert abs((end - start) - 0.005) < 1e-6


# -- dominance ---------------------------------------------------------


def test_dominant_stage_top_level():
    assert tracing.dominant_stage(
        {"admission_queue_wait": 1.2, "upstream_connect": 0.01,
         "upstream_ttfb": 0.3}
    ) == "admission_queue_wait"


def test_dominant_stage_descends_into_replica_refinement():
    """When the upstream span wins, the replica breakdown nested
    inside it names the true culprit instead of 'the upstream'."""
    assert tracing.dominant_stage(
        {"admission_queue_wait": 0.1, "upstream_ttfb": 2.0,
         "replica.prefill": 0.2, "replica.decode": 1.7}
    ) == "replica.decode"


def test_dominant_stage_replica_only_and_empty():
    assert tracing.dominant_stage(
        {"slot_queue_wait": 0.5, "decode": 0.1}
    ) == "slot_queue_wait"
    assert tracing.dominant_stage({}) is None
    assert tracing.dominant_stage({"x": 0.0}) is None


# -- engine-timings bridge ---------------------------------------------


def test_add_engine_spans_is_bounded_and_batched():
    """However long the decode ran (rounds, tokens), the engine hands
    over FOUR floats and one int — the span conversion emits at most
    three spans. This is the no-per-token-record contract."""
    rec = tracing.TraceRecorder("replica")
    trace = rec.start(endpoint="generate")
    timings = {
        "enqueued": 100.0, "admitted": 100.2,
        "prefill_done": 100.5, "done": 190.0, "rounds": 100_000,
    }
    tracing.add_engine_spans(trace, timings)
    assert [s[0] for s in trace.spans] == [
        "slot_queue_wait", "prefill", "decode"
    ]
    assert trace.spans[-1][3] == {"rounds": 100_000}
    # a spill-tier readmit carves a kv span OUT of the admission
    # window: kv + prefill together still span admitted ->
    # prefill_done, non-overlapping
    t_kv = rec.start(endpoint="generate")
    tracing.add_engine_spans(t_kv, dict(timings, kv=0.1))
    stages = {s[0]: s for s in t_kv.spans}
    assert set(stages) == {
        "slot_queue_wait", "kv", "prefill", "decode"
    }
    assert stages["kv"][1] == 100.2
    assert stages["kv"][2] == pytest.approx(100.3)
    assert stages["prefill"][1] == stages["kv"][2]
    assert stages["prefill"][2] == 100.5
    # a kv time exceeding the whole window clamps (never a negative
    # prefill span)
    t_clamp = rec.start(endpoint="generate")
    tracing.add_engine_spans(t_clamp, dict(timings, kv=99.0))
    stages = {s[0]: s for s in t_clamp.spans}
    assert stages["kv"][2] == 100.5
    assert stages["prefill"][1] == stages["prefill"][2] == 100.5
    # partial stamps (request failed before admission) emit less,
    # never raise
    t2 = rec.start(endpoint="generate")
    tracing.add_engine_spans(t2, {"enqueued": 1.0})
    assert t2.spans == []


def test_add_engine_spans_abandoned_mid_decode_accounts_to_now():
    """A stream abandoned mid-decode converts its timings before the
    engine's cancel-retire path stamps ``done``/``rounds`` — the
    decode stage must still be accounted (prefill_done -> now), not
    dropped, or dominance misattributes seconds of decode."""
    rec = tracing.TraceRecorder("replica")
    trace = rec.start(endpoint="generate")
    start = tracing.now()
    timings = {
        "enqueued": start - 0.5, "admitted": start - 0.45,
        "prefill_done": start - 0.4,  # no done, no rounds yet
    }
    tracing.add_engine_spans(trace, timings)
    stages = {s[0]: s for s in trace.spans}
    assert set(stages) == {"slot_queue_wait", "prefill", "decode"}
    _, d_start, d_end, _ = stages["decode"]
    assert d_start == start - 0.4
    # decode end is "the abandon instant": at/after prefill_done,
    # at/before the clock right after conversion
    assert d_start <= d_end <= tracing.now()


# -- log correlation ----------------------------------------------------


def test_json_logger_injects_trace_and_stream_id(tmp_path):
    log_file = tmp_path / "cp.json.log"
    LogConfig(
        {"level": "INFO", "format": "json", "output": str(log_file)}
    ).init()
    logger = logging.getLogger("containerpilot.test")
    rec = tracing.TraceRecorder("replica")
    trace = rec.start(trace_id="beef0000beef0000", endpoint="generate")
    token = tracing.activate(trace)
    stream_token = tracing.set_stream_id(7)
    try:
        logger.info("inside the request")
    finally:
        tracing.deactivate(token)
        tracing._stream.reset(stream_token)  # noqa: SLF001
    logger.info("outside the request")
    for handler in logging.getLogger("containerpilot").handlers:
        handler.flush()
    lines = [
        json.loads(line)
        for line in log_file.read_text().strip().splitlines()
    ]
    assert lines[0]["trace_id"] == "beef0000beef0000"
    assert lines[0]["stream_id"] == 7
    assert "trace_id" not in lines[1] and "stream_id" not in lines[1]


# -- build info ---------------------------------------------------------


def test_build_info_gauge_registered_once_per_registry():
    from prometheus_client import CollectorRegistry, generate_latest

    registry = CollectorRegistry()
    ensure_build_info(registry, "replica")
    ensure_build_info(registry, "replica")  # reload: no crash
    body = generate_latest(registry).decode()
    assert 'cp_build_info{' in body
    assert 'role="replica"' in body and "version=" in body


# -- client-side propagation (httpclient) -------------------------------


def test_keepalive_request_carries_active_trace_header(run):
    """A control/catalog call made while a traced request is active
    carries its X-CP-Trace — callers propagate by running the sync
    client under a copied context."""
    import contextvars

    seen = {}

    async def scenario():
        server = HTTPServer()

        async def handler(req: Request) -> Response:
            seen.update(req.headers)
            return Response(200, b"ok\n")

        server.route("GET", "/probe", handler)
        await server.start_tcp("127.0.0.1", 0)
        port = server.bound_port
        rec = tracing.TraceRecorder("test")
        trace = rec.start(trace_id="feed0123feed0123")
        token = tracing.activate(trace)

        def call():
            conns = []
            return keepalive_request(
                lambda: None,
                conns.append,
                lambda: http.client.HTTPConnection(
                    "127.0.0.1", port, timeout=10
                ),
                "GET", "/probe",
            )

        ctx = contextvars.copy_context()
        try:
            status, _body = await asyncio.get_event_loop(
            ).run_in_executor(None, ctx.run, call)
        finally:
            tracing.deactivate(token)
        await server.stop()
        return status

    assert run(scenario()) == 200
    assert seen.get("x-cp-trace") == "feed0123feed0123"
