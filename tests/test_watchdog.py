"""StepWatchdog: step-deadline failure detection for distributed
training (parallel/watchdog.py). The exit path is ``os._exit``, so the
firing tests run the dog in a subprocess and assert on its exit code.

The module is deliberately stdlib-only; it is loaded here by file path
(not through ``containerpilot_tpu.parallel``, whose __init__ imports
jax/orbax) so these tests stay in the fast no-JAX supervisor tier.
"""
import importlib.util
import os
import subprocess
import sys
import time

import pytest

_WATCHDOG_PY = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "containerpilot_tpu", "parallel", "watchdog.py",
)
_spec = importlib.util.spec_from_file_location("_watchdog", _WATCHDOG_PY)
_watchdog = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_watchdog)
EXIT_CODE = _watchdog.EXIT_CODE
StepWatchdog = _watchdog.StepWatchdog


def _run_dog(body: str, timeout: float = 30) -> subprocess.CompletedProcess:
    prog = (
        "import importlib.util\n"
        f"spec = importlib.util.spec_from_file_location("
        f"'_watchdog', {_WATCHDOG_PY!r})\n"
        "m = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(m)\n"
        "StepWatchdog = m.StepWatchdog\n"
        "import time\n" + body
    )
    return subprocess.run(
        [sys.executable, "-c", prog], capture_output=True, text=True,
        timeout=timeout,
    )


def test_beats_keep_it_alive():
    # generous deadline: a short scheduler pause between beats must
    # not os._exit the whole pytest run
    dog = StepWatchdog(5.0).start()
    for _ in range(3):
        time.sleep(0.2)
        dog.beat()
    dog.stop()  # never fired: we are still here to say so


def test_fires_without_beats():
    res = _run_dog(
        "StepWatchdog(0.3).start()\n"
        "time.sleep(30)\n"
    )
    assert res.returncode == EXIT_CODE, res.stderr


def test_stop_disarms():
    dog = StepWatchdog(0.3).start()
    dog.stop()
    time.sleep(0.6)  # would have fired (and killed pytest) if armed


def test_startup_grace_covers_first_beat_only():
    # deadline 0.3s but grace 2s: silence at t=0.6 must NOT fire;
    # after the first beat the tight deadline applies and fires
    res = _run_dog(
        "dog = StepWatchdog(0.3).start(grace_s=2.0)\n"
        "time.sleep(0.6)\n"      # inside grace: survives
        "dog.beat()\n"           # grace over; deadline now 0.3
        "time.sleep(30)\n"
    )
    assert res.returncode == EXIT_CODE, res.stderr


def test_grace_eventually_fires():
    res = _run_dog(
        "StepWatchdog(0.2).start(grace_s=0.5)\n"
        "time.sleep(30)\n"
    )
    assert res.returncode == EXIT_CODE, res.stderr


def test_grace_below_timeout_rejected():
    with pytest.raises(ValueError):
        StepWatchdog(5.0).start(grace_s=1.0)


def test_nonpositive_timeout_rejected():
    with pytest.raises(ValueError):
        StepWatchdog(0.0)
