"""Flash block tuning: table lookup, routing, and the autotuner
(ops/tuning.py, ops/autotune.py)."""
import jax.numpy as jnp
import pytest

from containerpilot_tpu.models.transformer import (
    TransformerConfig,
    flash_eligible,
)
from containerpilot_tpu.ops import tuning
from containerpilot_tpu.ops.autotune import build_table, measure


@pytest.fixture(autouse=True)
def reset_table():
    yield
    tuning.set_table(None)
    tuning._loaded = False  # rediscover from disk next lookup


SAMPLE = {
    "platform": "test",
    "flash_min_seq": {"train": 2048, "fwd": 1024},
    "blocks": {
        "train": {"2048": [256, 128], "8192": [512, 256]},
        "fwd": {"1024": [128, 128]},
    },
}


def test_pick_blocks_exact_and_nearest_below():
    tuning.set_table(SAMPLE)
    assert tuning.pick_blocks("train", 2048) == (256, 128)
    # 4096 has no entry: nearest tuned seq at/below is 2048
    assert tuning.pick_blocks("train", 4096) == (256, 128)
    assert tuning.pick_blocks("train", 8192) == (512, 256)


def test_pick_blocks_clamps_to_divisors():
    tuning.set_table(SAMPLE)
    # 2176 = 17*128 (odd multiple): 256 does not divide it; the tuned
    # 256 clamps down to 128
    bq, bk = tuning.pick_blocks("train", 2176)
    assert 2176 % bq == 0 and 2176 % bk == 0
    assert (bq, bk) == (128, 128)


def test_pick_blocks_default_without_table():
    tuning.set_table(None)
    tuning._loaded = True  # simulate: discovery ran, nothing found
    assert tuning.pick_blocks("train", 4096) == (128, 128)
    assert tuning.auto_min_seq("train") == tuning.DEFAULT_MIN_SEQ


def test_resolve_min_seq_sentinels():
    tuning.set_table(SAMPLE)
    assert tuning.resolve_min_seq(tuning.AUTO, "train") == 2048
    assert tuning.resolve_min_seq(tuning.AUTO, "fwd") == 1024
    # explicit values win unchanged; 0 still means never
    assert tuning.resolve_min_seq(512, "train") == 512
    assert tuning.resolve_min_seq(0, "train") == 0


def test_flash_eligible_resolves_auto_through_table():
    tuning.set_table(SAMPLE)
    cfg = TransformerConfig(
        d_model=64, n_heads=2, n_layers=1, d_ff=128,
        max_seq_len=8192, dtype=jnp.float32,  # flash_min_seq = AUTO
    )
    assert not flash_eligible(cfg, 1024)   # below tuned train crossover
    assert flash_eligible(cfg, 2048)
    # inference prefill resolves through the separately tuned 'fwd'
    # crossover (models/decode.py passes kind="fwd")
    assert flash_eligible(cfg, 1024, kind="fwd")
    # explicit config still wins over the table
    cfg_explicit = TransformerConfig(
        d_model=64, n_heads=2, n_layers=1, d_ff=128,
        max_seq_len=8192, dtype=jnp.float32, flash_min_seq=1024,
    )
    assert flash_eligible(cfg_explicit, 1024)


def test_build_table_crossover_requires_wins_through_the_top():
    # flash loses at 4096: the crossover must sit above it even though
    # 2048 nominally won
    results = {
        "2048": {"xla_fwd_ms": 10, "xla_train_ms": 30,
                 "flash": {"128x128": {"fwd_ms": 8, "train_ms": 25}}},
        "4096": {"xla_fwd_ms": 40, "xla_train_ms": 120,
                 "flash": {"128x128": {"fwd_ms": 50, "train_ms": 130}}},
        "8192": {"xla_fwd_ms": 160, "xla_train_ms": 500,
                 "flash": {"128x128": {"fwd_ms": 20, "train_ms": 100}}},
    }
    table = build_table(results, "test")
    assert table["flash_min_seq"]["train"] == 8192
    assert table["flash_min_seq"]["fwd"] == 8192
    assert table["blocks"]["train"]["2048"] == [128, 128]


def test_build_table_flash_never_wins():
    results = {
        "2048": {"xla_fwd_ms": 1, "xla_train_ms": 1,
                 "flash": {"128x128": {"fwd_ms": 2, "train_ms": 2}}},
    }
    table = build_table(results, "test")
    # above every measured seq: flash stays available for the
    # unmeasured long tail but never claims a measured loss
    assert table["flash_min_seq"]["train"] == 2049


def test_build_table_picks_fastest_blocks_per_kind():
    results = {
        "2048": {
            "xla_fwd_ms": 100, "xla_train_ms": 100,
            "flash": {
                "128x128": {"fwd_ms": 5, "train_ms": 9},
                "256x128": {"fwd_ms": 7, "train_ms": 3},
            },
        },
    }
    table = build_table(results, "test")
    assert table["blocks"]["fwd"]["2048"] == [128, 128]
    assert table["blocks"]["train"]["2048"] == [256, 128]


def test_build_table_honesty_guard_rejects_noise_wins():
    # 256x128 "wins" fwd by nothing (ties) and loses train: neither may
    # displace the 128/128 default; speedups are recorded per entry
    results = {
        "2048": {
            "xla_fwd_ms": 100, "xla_train_ms": 100,
            "flash": {
                "128x128": {"fwd_ms": 5.0, "train_ms": 9.0},
                "256x128": {"fwd_ms": 5.0, "train_ms": 10.0},
            },
        },
        "4096": {
            "xla_fwd_ms": 100, "xla_train_ms": 100,
            "flash": {
                "128x128": {"fwd_ms": 20.0, "train_ms": 40.0},
                "256x256": {"fwd_ms": 10.0, "train_ms": 30.0},
            },
        },
    }
    table = build_table(results, "test")
    assert table["blocks"]["fwd"]["2048"] == [128, 128]
    assert table["blocks"]["train"]["2048"] == [128, 128]
    # a real win still ships, with its measured margin
    assert table["blocks"]["fwd"]["4096"] == [256, 256]
    assert table["speedup_vs_default"]["fwd"]["4096"] == 2.0
    assert table["speedup_vs_default"]["train"]["2048"] == 1.0


def test_pick_blocks_rejects_non_tile_seq_loudly():
    # a seq that isn't a 128-multiple can't be clamped to any honest
    # block (100 isn't tileable, halving to 2 is degenerate): the
    # public helper must fail loudly, not feed pallas a bad grid
    for seq in (64, 100, 192, 2050):
        with pytest.raises(ValueError, match="flash blocks require"):
            tuning.pick_blocks("train", seq)
    # 128-multiples keep clamping to true divisors
    bq, bk = tuning.pick_blocks("train", 2176)
    assert 2176 % bq == 0 and 2176 % bk == 0


def test_autotune_measure_smoke():
    """End-to-end measure() on the CPU backend (interpret-mode pallas):
    tiny shapes, one candidate — asserts structure and positivity."""
    results = measure(
        [256], blocks=[128], batch=1, heads=1, head_dim=64, n=1, reps=1
    )
    entry = results["256"]
    assert entry["xla_fwd_ms"] > 0 and entry["xla_train_ms"] > 0
    flash = entry["flash"]["128x128"]
    assert flash["fwd_ms"] > 0 and flash["train_ms"] > 0
    table = build_table(results, "cpu-test")
    assert table["blocks"]["train"]["256"] == [128, 128]
    assert set(table["flash_min_seq"]) == {"train", "fwd"}
