"""Control-plane tests: unix-socket HTTP server + client SDK
(reference: control/control_test.go, client/client_test.go)."""
import asyncio
import os

import pytest

from containerpilot_tpu.client import ControlClient, ControlClientError
from containerpilot_tpu.control import ControlConfig, ControlServer
from containerpilot_tpu.events import (
    Event,
    EventBus,
    EventCode,
    GLOBAL_ENTER_MAINTENANCE,
    GLOBAL_EXIT_MAINTENANCE,
)


@pytest.fixture
def socket_path(tmp_path):
    return str(tmp_path / "cp.socket")


def drive(run, socket_path, fn):
    """Start a control server, run fn(client) in a thread, return the
    bus ring + fn result."""

    async def scenario():
        bus = EventBus()
        server = ControlServer(ControlConfig({"socket": socket_path}))
        await server.run(bus)
        client = ControlClient(socket_path)
        result = await asyncio.get_event_loop().run_in_executor(
            None, fn, client
        )
        await server.stop()
        return bus, result

    return run(scenario())


def test_ping(run, socket_path):
    bus, result = drive(run, socket_path, lambda c: c.get_ping())
    assert result is True


def test_putenv_sets_supervisor_environ(run, socket_path):
    drive(run, socket_path, lambda c: c.put_env({"CP_TEST_ENVVAR": "42"}))
    assert os.environ.pop("CP_TEST_ENVVAR") == "42"


def test_putmetric_publishes_metric_events(run, socket_path):
    bus, _ = drive(
        run, socket_path, lambda c: c.put_metric({"zz_sensor": 1.5})
    )
    assert Event(EventCode.METRIC, "zz_sensor|1.5") in bus.debug_events()


def test_maintenance_events(run, socket_path):
    def toggle(c):
        c.set_maintenance(True)
        c.set_maintenance(False)

    bus, _ = drive(run, socket_path, toggle)
    ring = bus.debug_events()
    assert GLOBAL_ENTER_MAINTENANCE in ring
    assert GLOBAL_EXIT_MAINTENANCE in ring


def test_reload_sets_flag_and_shuts_down(run, socket_path):
    bus, _ = drive(run, socket_path, lambda c: c.reload())
    assert bus.get_reload_flag() is True
    assert Event(EventCode.SHUTDOWN, "global") in bus.debug_events()


def test_stale_socket_rebind(run, socket_path):
    """A lingering socket file from a dead generation must not block a
    new bind (reference: control/control.go:125-140)."""
    with open(socket_path, "w") as f:
        f.write("")  # stale plain file at the socket path

    bus, result = drive(run, socket_path, lambda c: c.get_ping())
    assert result is True


def test_client_error_when_no_server(socket_path):
    client = ControlClient(socket_path, timeout=0.5)
    with pytest.raises(ControlClientError):
        client.get_ping()


def test_bad_body_is_422(run, socket_path):
    def post_bad(c):
        try:
            c.put_env(["not", "a", "dict"])  # type: ignore[arg-type]
        except ControlClientError as exc:
            return str(exc)
        return None

    _bus, err = drive(run, socket_path, post_bad)
    assert err is not None and "422" in err


def test_get_events_exposes_debug_ring(run, socket_path):
    def fn(c):
        c.put_metric({"zz_ring_probe": 1})
        return c.get_events()

    _bus, events = drive(run, socket_path, fn)
    assert {"code": "metric", "source": "zz_ring_probe|1"} in events


def test_get_tasks_lists_live_actors(run, socket_path):
    _bus, tasks = drive(run, socket_path, lambda c: c.get_tasks())
    assert isinstance(tasks, list) and tasks, "at least the handler task"
    assert all(isinstance(t, str) for t in tasks)


def test_slow_client_times_out():
    """A connection that sends nothing must not pin the server (slow
    loris): the read timeout closes it with 408."""
    import asyncio as aio
    import socket as sock

    from containerpilot_tpu.utils.http import HTTPServer, Response

    async def scenario():
        server = HTTPServer()
        server.REQUEST_READ_TIMEOUT = 0.3

        async def ok(_req):
            return Response(200, b"fine\n")

        server.route("GET", "/ok", ok)
        await server.start_tcp("127.0.0.1", 0)
        port = server.bound_port
        loop = aio.get_event_loop()

        def stall():
            s = sock.create_connection(("127.0.0.1", port), timeout=5)
            try:
                s.sendall(b"GET /ok HTTP/1.1\r\n")  # never finishes headers
                return s.recv(200)
            finally:
                s.close()

        data = await loop.run_in_executor(None, stall)
        await server.stop()
        return data

    import asyncio

    data = asyncio.run(scenario())
    assert b"408" in data


def test_protocol_errors_get_400_not_500(run, socket_path):
    """Malformed Content-Length (negative, non-numeric) and non-UTF-8
    bytes are CLIENT errors: 400, never a 500 + stack trace."""

    async def scenario():
        bus = EventBus()
        server = ControlServer(ControlConfig({"socket": socket_path}))
        await server.run(bus)

        async def raw(request_bytes: bytes) -> bytes:
            reader, writer = await asyncio.open_unix_connection(socket_path)
            writer.write(request_bytes)
            await writer.drain()
            response = await reader.read(4096)
            writer.close()
            return response

        results = [
            await raw(b"GET /v3/ping HTTP/1.1\r\nContent-Length: -1\r\n\r\n"),
            await raw(b"GET /v3/ping HTTP/1.1\r\nContent-Length: nope\r\n\r\n"),
            await raw(b"GET /v3/ping HTTP/1.1\r\nX-Bad: \xff\xfe\r\n\r\n"),
            await raw(b"\xff\xfe malformed\r\n\r\n"),
        ]
        await server.stop()
        return results

    for response in run(scenario()):
        assert response.startswith(b"HTTP/1.1 400"), response[:60]


def test_maintenance_status_reads_back_the_flip(run, socket_path):
    """Drain runbooks confirm maintenance landed: the status endpoint
    tracks the last verb posted through this generation's socket."""

    def toggle_and_read(c):
        before = c.get_maintenance_status()
        c.set_maintenance(True)
        during = c.get_maintenance_status()
        c.set_maintenance(False)
        after = c.get_maintenance_status()
        return before, during, after

    _bus, (before, during, after) = drive(run, socket_path, toggle_and_read)
    assert (before, during, after) == (False, True, False)


def test_client_retries_connect_while_supervisor_boots(run, socket_path):
    """The first control call after `containerpilot start` races the
    socket bind; ECONNREFUSED/ENOENT during that window retries with
    backoff instead of failing the call."""

    async def scenario():
        bus = EventBus()
        server = ControlServer(ControlConfig({"socket": socket_path}))
        client = ControlClient(
            socket_path, timeout=2.0, retries=8, retry_delay=0.05
        )
        loop = asyncio.get_event_loop()
        # the client starts dialing BEFORE the socket exists
        ping = loop.run_in_executor(None, client.get_ping)
        await asyncio.sleep(0.15)
        await server.run(bus)
        result = await ping
        await server.stop()
        return result

    assert run(scenario(), timeout=30) is True


def test_client_reuses_control_connection_across_verbs(run, socket_path):
    """The client keeps ONE unix-socket connection across verbs (the
    control server speaks keep-alive): an SDK posting a metric every
    training step must not dial per call."""

    async def scenario():
        bus = EventBus()
        server = ControlServer(ControlConfig({"socket": socket_path}))
        await server.run(bus)

        def verbs(c):
            c.get_ping()
            c.put_metric({"zz_keepalive_probe": 1})
            c.get_maintenance_status()
            c.get_events()
            return True

        with ControlClient(socket_path) as client:
            result = await asyncio.get_event_loop().run_in_executor(
                None, verbs, client
            )
        http_server = server._server  # noqa: SLF001
        counters = (
            http_server.connections_accepted,
            http_server.requests_served,
        )
        await server.stop()
        return result, counters

    result, (conns, reqs) = run(scenario(), timeout=30)
    assert result is True
    assert conns == 1 and reqs == 4  # four verbs, one dial


def test_client_redials_after_server_restart(run, socket_path):
    """A kept connection from a previous server generation is stale;
    the next verb must transparently redial, not error out."""

    async def scenario():
        bus = EventBus()
        loop = asyncio.get_event_loop()
        server = ControlServer(ControlConfig({"socket": socket_path}))
        await server.run(bus)
        client = ControlClient(socket_path)
        first = await loop.run_in_executor(None, client.get_ping)
        await server.stop()  # kept client connection force-closed
        server2 = ControlServer(ControlConfig({"socket": socket_path}))
        await server2.run(EventBus())
        second = await loop.run_in_executor(None, client.get_ping)
        client.close()
        await server2.stop()
        return first, second

    assert run(scenario(), timeout=30) == (True, True)
