"""Consul wire-format evidence for discovery/consul.py.

Two tiers, mirroring the reference's posture:

1. **Golden wire-format tests** against a recording HTTP server: every
   Backend method must emit exactly the method/path/query/body the
   Consul agent HTTP API specifies (the reference gets this for free by
   vendoring the official client; we assert it explicitly).
2. **Live-agent tests** against a real `consul agent -dev` binary when
   one is on $PATH, else against the wire-compatible emulator
   (discovery/consul_emulator.py) — they run either way (reference:
   discovery/test_server.go:19-56, which `make tools` fetches; this
   environment has no egress, hence the emulator fallback).
"""
import http.server
import json
import shutil
import socket
import subprocess
import sys
import threading
import time

import pytest

from containerpilot_tpu.discovery.backend import ServiceRegistration
from containerpilot_tpu.discovery.consul import ConsulBackend


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _Recorder(http.server.BaseHTTPRequestHandler):
    """Records every request; answers 200 with a canned body."""

    requests = []
    responses = {}

    def _handle(self):
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        type(self).requests.append(
            {
                "method": self.command,
                "path": self.path,
                "headers": dict(self.headers),
                "body": json.loads(body) if body else None,
            }
        )
        payload = b"null"
        for prefix, canned in type(self).responses.items():
            if self.path.startswith(prefix):
                payload = json.dumps(canned).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    do_GET = do_PUT = do_POST = _handle

    def log_message(self, *args):  # noqa: D102 - silence
        pass


@pytest.fixture()
def recorder():
    _Recorder.requests = []
    _Recorder.responses = {}
    port = free_port()
    server = http.server.ThreadingHTTPServer(("127.0.0.1", port), _Recorder)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield port, _Recorder
    server.shutdown()
    thread.join(timeout=5)


def test_register_wire_format(recorder):
    """PUT /v1/agent/service/register with the documented body schema,
    including the TTL check and DeregisterCriticalServiceAfter."""
    port, rec = recorder
    backend = ConsulBackend(address=f"127.0.0.1:{port}", token="tok-123")
    backend.service_register(
        ServiceRegistration(
            id="web-1", name="web", port=8080, address="10.1.2.3",
            ttl=10, tags=["a", "b"],
            deregister_critical_service_after="90m",
            enable_tag_override=True,
        ),
        status="passing",
    )
    (req,) = rec.requests
    assert req["method"] == "PUT"
    assert req["path"] == "/v1/agent/service/register"
    assert req["headers"]["X-Consul-Token"] == "tok-123"
    body = req["body"]
    assert body["ID"] == "web-1"
    assert body["Name"] == "web"
    assert body["Port"] == 8080
    assert body["Address"] == "10.1.2.3"
    assert body["Tags"] == ["a", "b"]
    assert body["EnableTagOverride"] is True
    check = body["Check"]
    assert check["TTL"] == "10s"
    assert check["Status"] == "passing"
    assert check["DeregisterCriticalServiceAfter"] == "90m"


def test_deregister_and_ttl_wire_format(recorder):
    port, rec = recorder
    backend = ConsulBackend(address=f"127.0.0.1:{port}")
    backend.service_deregister("web-1")
    backend.update_ttl("service:web-1", "ok", "pass")
    dereg, ttl = rec.requests
    assert dereg["method"] == "PUT"
    assert dereg["path"] == "/v1/agent/service/deregister/web-1"
    assert ttl["method"] == "PUT"
    # check ids keep their raw colon (path-segment-legal; the reference
    # client sends them unescaped)
    assert ttl["path"] == "/v1/agent/check/update/service:web-1"
    assert ttl["body"] == {"Output": "ok", "Status": "passing"}


def test_health_query_wire_format(recorder):
    """GET /v1/health/service/<name>?passing=1[&tag=..&dc=..] and the
    documented response envelope is decoded into instances."""
    port, rec = recorder
    rec.responses["/v1/health/service/web"] = [
        {
            "Node": {"Node": "n1", "Address": "10.0.0.9"},
            "Service": {
                "ID": "web-1", "Service": "web",
                "Address": "10.1.2.3", "Port": 8080,
            },
        },
        {
            "Node": {"Node": "n2", "Address": "10.0.0.10"},
            # no Service.Address -> Node.Address per the API contract
            "Service": {"ID": "web-2", "Service": "web", "Port": 8081},
        },
    ]
    backend = ConsulBackend(address=f"127.0.0.1:{port}")
    instances = backend.instances("web")
    (req,) = rec.requests
    assert req["method"] == "GET"
    path, _, query = req["path"].partition("?")
    assert path == "/v1/health/service/web"
    assert "passing=1" in query
    assert [(i.id, i.address, i.port) for i in instances] == [
        ("web-1", "10.1.2.3", 8080),
        ("web-2", "10.0.0.10", 8081),
    ]

    rec.requests.clear()
    backend.check_for_upstream_changes("web", tag="prod", dc="dc two")
    (req,) = rec.requests
    _, _, query = req["path"].partition("?")
    # urlencoded: the space in dc must not corrupt the query string
    assert "tag=prod" in query
    assert "dc=dc+two" in query or "dc=dc%20two" in query


def test_weird_service_names_are_encoded(recorder):
    port, rec = recorder
    backend = ConsulBackend(address=f"127.0.0.1:{port}")
    backend.instances("a&b=c d")
    (req,) = rec.requests
    path, _, _ = req["path"].partition("?")
    assert path == "/v1/health/service/a%26b%3Dc%20d"


# ---------------------------------------------------------------------------
# live agent: a real consul binary when one is on $PATH, else the
# framework's own consul-wire-compatible catalog-server daemon — either
# way the lifecycle tests below run against a live agent with real
# TTL-check state transitions (expiry -> critical, critical-too-long ->
# reaped), mirroring the reference's consul test server
# (discovery/test_server.go:19-56, fetched by `make tools`; this
# environment has no egress, hence the built-in daemon fallback).
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def consul_agent():
    if shutil.which("consul") is None:
        import os
        import urllib.request

        port = free_port()
        repo = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        proc = subprocess.Popen(
            [sys.executable, "-m", "containerpilot_tpu",
             "-catalog-server", f"127.0.0.1:{port}"],
            cwd=repo, env=dict(os.environ, PYTHONPATH=repo),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        deadline = time.monotonic() + 30
        while True:
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/v1/health/service/none",
                    timeout=1,
                )
                break
            except Exception:
                if time.monotonic() > deadline:
                    proc.terminate()
                    pytest.skip("catalog server never became ready")
                time.sleep(0.2)
        yield port
        proc.terminate()
        proc.wait(timeout=10)
        return
    port = free_port()
    proc = subprocess.Popen(
        ["consul", "agent", "-dev", f"-http-port={port}",
         "-bind=127.0.0.1", "-log-level=err"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    import urllib.request

    deadline = time.monotonic() + 30
    while True:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/status/leader", timeout=1
            )
            break
        except Exception:
            if time.monotonic() > deadline:
                proc.terminate()
                pytest.skip("consul agent never became ready")
            time.sleep(0.3)
    yield port
    proc.terminate()
    proc.wait(timeout=10)


def test_register_heartbeat_query_against_real_consul(consul_agent):
    """The full lifecycle against an actual consul agent -dev
    (reference: discovery/test_server.go + consul_test.go)."""
    backend = ConsulBackend(address=f"127.0.0.1:{consul_agent}")
    backend.service_register(
        ServiceRegistration(
            id="trainer-1", name="trainer", port=4000,
            address="127.0.0.1", ttl=30,
        ),
        status="passing",
    )
    instances = backend.instances("trainer")
    assert [(i.id, i.port) for i in instances] == [("trainer-1", 4000)]
    backend.update_ttl("service:trainer-1", "healthy", "pass")
    changed, healthy = backend.check_for_upstream_changes("trainer")
    assert healthy
    backend.service_deregister("trainer-1")
    deadline = time.monotonic() + 10
    while backend.instances("trainer"):
        assert time.monotonic() < deadline, "deregister never took effect"
        time.sleep(0.2)


def test_ttl_expiry_goes_critical_then_deregisters(consul_agent):
    """Agent-side TTL semantics: a service whose TTL check is not
    refreshed leaves the passing set, and one critical longer than
    DeregisterCriticalServiceAfter is dropped entirely — the behavior
    the supervisor's health loop and watches depend on. Runs against
    whichever live agent the fixture provided."""
    backend = ConsulBackend(address=f"127.0.0.1:{consul_agent}")
    backend.service_register(
        ServiceRegistration(
            id="flaky-1", name="flaky", port=4100, address="127.0.0.1",
            ttl=1, deregister_critical_service_after="2s",
        ),
        status="passing",
    )
    assert [i.id for i in backend.instances("flaky")] == ["flaky-1"]
    # no heartbeat: past the TTL the passing filter must exclude it
    deadline = time.monotonic() + 10
    while backend.instances("flaky"):
        assert time.monotonic() < deadline, "TTL expiry never took effect"
        time.sleep(0.3)
    if shutil.which("consul") is not None:
        # real Consul clamps DeregisterCriticalServiceAfter to a
        # 1-minute minimum and reaps on a 30s cycle — the fast
        # reap below would wait minutes; TTL->critical is the part
        # asserted against the real agent
        backend.service_deregister("flaky-1")
        return
    # critical past DeregisterCriticalServiceAfter: gone from the agent
    deadline = time.monotonic() + 15
    while True:
        changed, healthy = backend.check_for_upstream_changes("flaky")
        if not healthy:
            sweep = backend.instances("flaky")
            if not sweep:
                break
        assert time.monotonic() < deadline, "dereg-after never took effect"
        time.sleep(0.3)


def test_heartbeat_keeps_service_passing(consul_agent):
    """Refreshed TTLs stay passing across several TTL windows."""
    backend = ConsulBackend(address=f"127.0.0.1:{consul_agent}")
    backend.service_register(
        ServiceRegistration(
            id="steady-1", name="steady", port=4200,
            address="127.0.0.1", ttl=1,
        ),
        status="passing",
    )
    try:
        for _ in range(4):
            time.sleep(0.5)
            backend.update_ttl("service:steady-1", "ok", "pass")
            assert [i.id for i in backend.instances("steady")] == [
                "steady-1"
            ]
    finally:
        backend.service_deregister("steady-1")
