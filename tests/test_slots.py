"""Slot-based continuous decode (models/slots.py +
workload/serve_slots.py): per-request byte-parity with solo generate,
staggered admission, eos handling, and pool churn."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from containerpilot_tpu.models.decode import generate
from containerpilot_tpu.models.transformer import (
    TransformerConfig,
    init_params,
)
from containerpilot_tpu.workload.serve_slots import SlotEngine

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
    max_seq_len=64, dtype=jnp.float32,
)
MAX_LEN = 48


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture()
def engine(params):
    eng = SlotEngine(CFG, params, MAX_LEN, slots=2, chunk=3)
    yield eng
    eng.stop()


def _solo(params, tokens, max_new, cfg=CFG, **kw):
    """Reference: solo generate with the SERVER's key convention (row
    i of a request samples from fold_in(PRNGKey(seed), i) — the same
    derivation the batcher/prefix/strategies paths use, so seeded
    output is identical across serving configs), trimmed the way the
    server trims (keep eos, drop the pads after it)."""
    seed = kw.pop("seed", 0)
    eos = kw.pop("eos_id", -1)
    out = generate(
        params, jnp.asarray([tokens], jnp.int32), cfg, max_new,
        MAX_LEN,
        rng=jnp.stack([jax.random.fold_in(jax.random.PRNGKey(seed), 0)]),
        eos_id=eos, **kw,
    )
    row = [int(t) for t in np.asarray(out)[0]]
    if eos >= 0 and eos in row:
        row = row[: row.index(eos) + 1]
    return row


def test_single_request_matches_generate_greedy(params, engine):
    tokens = [1, 2, 3, 4]
    got = engine.submit(tokens, max_new=7).result(timeout=120)
    assert got == _solo(params, tokens, 7)


def test_single_request_matches_generate_sampled(params, engine):
    tokens = [5, 6, 7]
    kw = dict(temperature=0.9, top_k=12, top_p=0.8, seed=11)
    got = engine.submit(tokens, max_new=9, **kw).result(timeout=120)
    assert got == _solo(params, tokens, 9, **kw)


def test_staggered_admission_is_isolated(params, engine):
    """A request admitted mid-flight (different prompt, different
    sampling, different arrival chunk) changes nothing for either
    row — both match their solo runs exactly."""
    a = engine.submit([1, 2, 3, 4, 5], max_new=12, temperature=0.7,
                      seed=3)
    # b arrives while a decodes (submission order is the only
    # coupling; the queue guarantees b joins at a later chunk)
    b = engine.submit([9, 8], max_new=5)
    assert a.result(timeout=180) == _solo(
        params, [1, 2, 3, 4, 5], 12, temperature=0.7, seed=3
    )
    assert b.result(timeout=180) == _solo(params, [9, 8], 5)


def test_eos_trims_like_generate(params, engine):
    """Force an early eos by finding the greedy second token, then
    asking for it as eos: the engine output must keep the eos and
    stop, matching the trimmed solo run."""
    tokens = [2, 4, 6]
    free = _solo(params, tokens, 6)
    eos = free[1]  # greedy decode is deterministic; token 1 will recur
    got = engine.submit(tokens, max_new=6, eos_id=eos).result(
        timeout=120
    )
    assert got == _solo(params, tokens, 6, eos_id=eos)
    # the chosen token may ALSO be the greedy first draw (numerics
    # vary across backends), so derive the expected stop point from
    # the free-running output instead of assuming position 1
    assert got[-1] == eos and len(got) == free.index(eos) + 1


def test_more_requests_than_slots_all_complete(params, engine):
    prompts = [[i + 1, i + 2] for i in range(5)]  # 5 reqs, 2 slots
    futs = [
        engine.submit(p, max_new=4, seed=i)
        for i, p in enumerate(prompts)
    ]
    for i, (p, f) in enumerate(zip(prompts, futs)):
        assert f.result(timeout=300) == _solo(params, p, 4, seed=i)


def test_submit_validation(params, engine):
    with pytest.raises(ValueError, match="prompt"):
        engine.submit([], max_new=4)
    with pytest.raises(ValueError, match="exceeds"):
        engine.submit([1] * 40, max_new=20)
    with pytest.raises(ValueError, match="max_new"):
        engine.submit([1, 2], max_new=0)


def test_chunk_failure_recovers_pool(params):
    """A failed chunk donates the pool buffer; the engine must
    rebuild it and keep serving instead of failing forever. The
    decode call lives in the step program now (models/stepprog.py),
    so that is where the fault injects; the first dispatch after an
    admission is always the single-chunk program, so the patch
    intercepts round one."""
    eng = SlotEngine(CFG, params, MAX_LEN, slots=2, chunk=2)
    try:
        import containerpilot_tpu.models.stepprog as mod

        original = mod.decode_slots_chunk
        calls = {"n": 0}

        def boom(*args, **kw):
            calls["n"] += 1
            if calls["n"] == 1:
                # donate like the real call would, then fail
                args[1]["k"].delete()
                raise RuntimeError("injected chunk failure")
            return original(*args, **kw)

        mod.decode_slots_chunk = boom
        try:
            failed = eng.submit([1, 2, 3], max_new=5)
            with pytest.raises(RuntimeError, match="injected"):
                failed.result(timeout=120)
        finally:
            mod.decode_slots_chunk = original
        # the pool was rebuilt: the next request serves normally
        ok = eng.submit([1, 2, 3], max_new=5)
        assert ok.result(timeout=120) == _solo(params, [1, 2, 3], 5)
    finally:
        eng.stop()


@pytest.fixture(scope="module")
def window_setup():
    """A sliding-window engine (ring caches, decode.py) plus params
    for the SAME windowed config — the solo reference must run the
    identical ring-cache path."""
    import dataclasses

    cfg = dataclasses.replace(CFG, window=8)
    params = init_params(jax.random.PRNGKey(0), cfg)
    eng = SlotEngine(cfg, params, MAX_LEN, slots=2, chunk=3)
    yield cfg, params, eng
    eng.stop()


def test_window_long_prompt_and_decode_cross_the_ring(window_setup):
    """Prompt longer than the window AND decode past the wrap point:
    every ring overwrite the engine performs matches solo generate."""
    cfg, params, eng = window_setup
    tokens = list(range(1, 13))  # 12 > window 8
    got = eng.submit(tokens, max_new=9).result(timeout=180)
    assert got == _solo(params, tokens, 9, cfg=cfg)


def test_window_slot_reuse_carries_no_stale_context(window_setup):
    """The historical hazard: a freed ring slot's cache rows are NOT
    zeroed, so re-admission must prove the wholesale row overwrite
    (insert_row) leaves nothing of the previous occupant. Fill both
    slots, finish them, then reuse with fresh prompts."""
    cfg, params, eng = window_setup
    first = [
        eng.submit([1, 2, 3, 4, 5, 6, 7, 8, 9], max_new=6, seed=1),
        eng.submit([9, 8, 7], max_new=6, seed=2),
    ]
    for fut in first:
        fut.result(timeout=180)
    reused = [
        ([5, 4, 3, 2], dict(max_new=10, seed=7)),
        ([2, 2], dict(max_new=10, temperature=0.8, top_k=16, seed=4)),
    ]
    futs = [eng.submit(p, **kw) for p, kw in reused]
    for (p, kw), fut in zip(reused, futs):
        assert fut.result(timeout=180) == _solo(
            params, p, kw.pop("max_new"), cfg=cfg, **kw
        )


def test_chunked_admission_matches_generate(params):
    """--prefill-chunk composes with the pool: admissions longer than
    the chunk prefill in fixed-size pieces (chunked_prefill) and the
    decode still byte-matches solo generate — long and short prompts,
    greedy and sampled, plus slot reuse over the chunked path."""
    eng = SlotEngine(CFG, params, MAX_LEN, slots=2, chunk=3,
                     prefill_chunk=4)
    try:
        long_p = [(i * 3 + 1) % 64 for i in range(11)]  # 11 > 4
        got = eng.submit(long_p, max_new=7).result(timeout=180)
        assert got == _solo(params, long_p, 7)
        # short prompts skip the chunked path entirely
        got = eng.submit([5, 6], max_new=5).result(timeout=180)
        assert got == _solo(params, [5, 6], 5)
        # sampled + reuse of the chunk-admitted slot
        kw = dict(temperature=0.9, top_k=12, seed=11)
        got = eng.submit(long_p, max_new=6, **kw).result(timeout=180)
        assert got == _solo(params, long_p, 6, **kw)
    finally:
        eng.stop()


def test_stats_and_stop(params):
    eng = SlotEngine(CFG, params, MAX_LEN, slots=3, chunk=2)
    stats = eng.stats
    assert stats["slots"] == 3 and stats["chunk"] == 2
    fut = eng.submit([1, 2], max_new=3)
    assert fut.result(timeout=120)
    eng.stop()
    with pytest.raises(RuntimeError):
        eng.submit([1, 2], max_new=3)


def test_inference_server_slot_engine(run, params):
    """Server-level: concurrent /v1/generate requests through --slots
    match sequential solo answers; /v1/model reports the engine."""
    import json
    import urllib.request

    from containerpilot_tpu.workload.serve import InferenceServer

    server = InferenceServer(
        CFG, params, "127.0.0.1", 0, max_len=MAX_LEN, slots=2,
        slot_chunk=4,
    )

    def fetch(path, body=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}{path}",
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"} if body else {},
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            return json.loads(resp.read().decode())

    async def scenario():
        import asyncio

        await server.run()
        loop = asyncio.get_event_loop()
        info = await loop.run_in_executor(None, lambda: fetch("/v1/model"))
        reqs = [
            {"tokens": [[1, 2, 3]], "max_new_tokens": 6,
             "temperature": 0.8, "seed": 5},
            {"tokens": [[7, 8]], "max_new_tokens": 4},
            {"tokens": [[4, 5, 6, 7]], "max_new_tokens": 5, "seed": 2,
             "temperature": 0.5, "top_k": 10},
        ]
        outs = await asyncio.gather(*[
            loop.run_in_executor(None, lambda r=r: fetch("/v1/generate", r))
            for r in reqs
        ])
        await server.stop()
        return info, outs

    info, outs = run(scenario())
    stats = dict(info["slot_engine"])
    # cumulative dispatch/token accounting (the goodput ledger's
    # dispatches/token pair): present, monotone, and bounded below
    # one dispatch per token for chunked decode
    assert stats.pop("dispatches") >= 1
    assert stats.pop("tokens_out") >= 1
    assert stats == {
        "slots": 2, "chunk": 4, "window": 4, "active": 0,
        "queued": 0,
    }
    assert outs[0]["tokens"][0] == _solo(
        params, [1, 2, 3], 6, temperature=0.8, seed=5
    )
    assert outs[1]["tokens"][0] == _solo(params, [7, 8], 4)
    assert outs[2]["tokens"][0] == _solo(
        params, [4, 5, 6, 7], 5, seed=2, temperature=0.5, top_k=10
    )


def test_stream_deltas_concatenate_to_result(params, engine):
    """on_tokens deltas, concatenated, ARE the final result — the
    streaming surface can't drift from the non-streamed one."""
    deltas = []
    got = engine.submit(
        [1, 2, 3], max_new=8, temperature=0.7, seed=11,
        on_tokens=deltas.append,
    ).result(timeout=120)
    assert sum(deltas, []) == got
    assert got == _solo(params, [1, 2, 3], 8, temperature=0.7, seed=11)
    # the first delta is the admission sample: streaming starts
    # before the row's decode finishes, not after
    assert len(deltas) >= 2 and len(deltas[0]) == 1


def test_cancel_frees_slot_mid_generation(params, engine):
    """A cancelled request releases its slot at the next chunk
    boundary with a partial emission; the pool keeps serving."""
    import threading

    cancel = threading.Event()
    first = threading.Event()
    partial = []

    def on_tokens(delta):
        partial.extend(delta)
        first.set()

    max_new = MAX_LEN - 3
    fut = engine.submit(
        [5, 6, 7], max_new=max_new, on_tokens=on_tokens, cancel=cancel,
    )
    assert first.wait(timeout=120), "no first token"
    cancel.set()
    got = fut.result(timeout=120)
    assert 0 < len(got) < max_new, (
        f"cancel did not stop decode early ({len(got)}/{max_new})"
    )
    # the slot is back in the pool and byte-parity still holds
    deadline = __import__("time").monotonic() + 30
    while engine.stats["active"]:
        assert __import__("time").monotonic() < deadline
        __import__("time").sleep(0.05)
    after = engine.submit([1, 2, 3, 4], max_new=7).result(timeout=120)
    assert after == _solo(params, [1, 2, 3, 4], 7)


def _read_sse(port, body, abort_after=None, path="/v1/generate"):
    """POST with stream:true and read SSE events as they arrive;
    abort_after closes the socket after that many events (a client
    disconnect mid-stream)."""
    import http.client
    import json as json_mod

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request(
        "POST", path, json_mod.dumps(body),
        {"Content-Type": "application/json"},
    )
    resp = conn.getresponse()
    assert resp.status == 200, resp.read()
    assert resp.getheader("Content-Type") == "text/event-stream"
    events = []
    buf = b""
    while True:
        chunk = resp.read1(65536)
        if not chunk:
            break
        buf += chunk
        while b"\n\n" in buf:
            raw, buf = buf.split(b"\n\n", 1)
            assert raw.startswith(b"data: "), raw
            events.append(json_mod.loads(raw[len(b"data: "):]))
            if abort_after is not None and len(events) >= abort_after:
                # hard disconnect: closing the response closes the
                # underlying socket (Connection: close responses own
                # it), which is the server's EOF signal
                resp.close()
                conn.close()
                return events
    conn.close()
    return events


def test_server_stream_matches_non_streamed(run, params):
    """Streamed tokens byte-match the non-streamed response, greedy
    and sampled; the terminal event reports the count."""
    import asyncio
    import json as json_mod
    import urllib.request

    from containerpilot_tpu.workload.serve import InferenceServer

    server = InferenceServer(
        CFG, params, "127.0.0.1", 0, max_len=MAX_LEN, slots=2,
        slot_chunk=3,
    )

    def fetch(path, body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}{path}",
            data=json_mod.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            return json_mod.loads(resp.read().decode())

    async def scenario():
        await server.run()
        loop = asyncio.get_event_loop()
        reqs = [
            {"tokens": [[1, 2, 3]], "max_new_tokens": 7},
            {"tokens": [[4, 5]], "max_new_tokens": 6,
             "temperature": 0.9, "top_k": 12, "seed": 3},
        ]
        results = []
        for body in reqs:
            plain = await loop.run_in_executor(
                None, lambda b=body: fetch("/v1/generate", b)
            )
            events = await loop.run_in_executor(
                None, lambda b=body: _read_sse(
                    server.port, dict(b, stream=True)
                )
            )
            results.append((plain, events))
        await server.stop()
        return results

    for plain, events in run(scenario()):
        assert events[-1]["done"] is True
        streamed = sum(
            (e["tokens"] for e in events if "tokens" in e), []
        )
        assert streamed == plain["tokens"][0]
        assert events[-1]["count"] == len(streamed)


def test_server_stream_disconnect_frees_slot(run, params):
    """Closing the connection mid-stream cancels the request: the
    slot returns to the pool well before the requested length could
    have decoded, and the server keeps serving."""
    import asyncio
    import json as json_mod
    import urllib.request

    from containerpilot_tpu.workload.serve import InferenceServer

    server = InferenceServer(
        CFG, params, "127.0.0.1", 0, max_len=MAX_LEN, slots=2,
        slot_chunk=2,
    )

    def fetch(path, body=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}{path}",
            data=json_mod.dumps(body).encode() if body else None,
            headers={"Content-Type": "application/json"} if body else {},
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            return json_mod.loads(resp.read().decode())

    async def scenario():
        import time as time_mod

        await server.run()
        loop = asyncio.get_event_loop()
        max_new = MAX_LEN - 3
        events = await loop.run_in_executor(
            None, lambda: _read_sse(
                server.port,
                {"tokens": [[7, 8, 9]], "max_new_tokens": max_new,
                 "stream": True},
                abort_after=1,
            )
        )
        assert len(events) == 1  # we left after the first token
        # the slot must come back without the row decoding to the end
        deadline = time_mod.monotonic() + 60
        while True:
            info = await loop.run_in_executor(
                None, lambda: fetch("/v1/model")
            )
            if info["slot_engine"]["active"] == 0:
                break
            assert time_mod.monotonic() < deadline, info
            await asyncio.sleep(0.1)
        # cancellation kept the token counter well under the request
        metrics = await loop.run_in_executor(
            None,
            lambda: urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/metrics", timeout=30
            ).read().decode(),
        )
        token_lines = [
            line for line in metrics.splitlines()
            if line.startswith("containerpilot_serve_generated_tokens_total")
        ]
        assert token_lines, "token counter missing from /metrics"
        for line in token_lines:
            assert float(line.split()[-1]) < max_new, line
        # and the pool still answers correctly
        after = await loop.run_in_executor(
            None, lambda: fetch(
                "/v1/generate",
                {"tokens": [[1, 2, 3]], "max_new_tokens": 5},
            )
        )
        await server.stop()
        return after

    after = run(scenario())
    assert after["tokens"][0] == _solo(params, [1, 2, 3], 5)


def test_stream_decoder_holds_back_split_multibyte():
    """Deterministic coverage of the holdback path the server test
    can't force (it depends on what the model happens to emit): a
    multibyte char split across deltas is buffered until complete,
    and a dangling prefix at stream end flushes as the SAME
    replacement char the one-shot decode produces."""
    from containerpilot_tpu.workload.text import (
        ByteTokenizer,
        stream_decoder,
    )

    tok = ByteTokenizer(512)
    e_acute = tok.encode("é", bos=False)  # 2 ids: 0xC3 0xA9
    assert len(e_acute) == 2

    # split across two deltas: nothing until the char completes
    delta_event, tail_events = stream_decoder(tok)
    first = delta_event([e_acute[0]])
    second = delta_event([e_acute[1]])
    assert first["text"] == "" and second["text"] == "é"
    assert tail_events() == []  # nothing dangling

    # dangling prefix at stream end: the flush event carries exactly
    # what decode() makes of the same ids
    delta_event, tail_events = stream_decoder(tok)
    assert delta_event([e_acute[0]])["text"] == ""
    (flush,) = tail_events()
    assert flush["tokens"] == []
    assert flush["text"] == tok.decode([e_acute[0]]) == "�"
    assert tail_events() == []  # flush is one-shot

    # specials interleaved: filtered identically to decode()
    delta_event, tail_events = stream_decoder(tok)
    parts = [
        delta_event([tok.EOS, e_acute[0]])["text"],
        delta_event([e_acute[1], tok.PAD])["text"],
    ]
    assert "".join(parts) == tok.decode(
        [tok.EOS, e_acute[0], e_acute[1], tok.PAD]
    ) == "é"


def test_server_completions_stream_matches_non_streamed(run):
    """Text SSE on /v1/completions: per-event text rides UTF-8
    partial-byte holdback, so concatenated event text equals the
    non-streamed 'text' and concatenated ids equal its 'tokens' —
    whatever byte sequences the model emits."""
    import asyncio
    import json as json_mod
    import urllib.request

    from containerpilot_tpu.workload.serve import InferenceServer

    cfg = TransformerConfig(
        vocab_size=512, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    server = InferenceServer(
        cfg, params, "127.0.0.1", 0, max_len=48, text=True, slots=2,
        slot_chunk=3,
    )

    def fetch(body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/completions",
            data=json_mod.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            return json_mod.loads(resp.read().decode())

    async def scenario():
        await server.run()
        loop = asyncio.get_event_loop()
        results = []
        for body in (
            {"prompt": "hé", "max_new_tokens": 9},  # multibyte prompt
            {"prompt": "ab", "max_new_tokens": 7,
             "temperature": 0.9, "seed": 4},
        ):
            plain = await loop.run_in_executor(
                None, lambda b=body: fetch(b)
            )
            events = await loop.run_in_executor(
                None, lambda b=body: _read_sse(
                    server.port, dict(b, stream=True),
                    path="/v1/completions",
                )
            )
            results.append((plain, events))
        await server.stop()
        return results

    for plain, events in run(scenario()):
        assert events[-1]["done"] is True
        toks = sum((e["tokens"] for e in events if "tokens" in e), [])
        text = "".join(e.get("text", "") for e in events[:-1])
        assert toks == plain["tokens"]
        assert text == plain["text"]
        assert events[-1]["count"] == len(toks)


def test_server_stream_rejects_bad_compositions(run, params):
    """stream without --slots, and stream+stop, fail with clean 422s
    before any decode starts."""
    import asyncio
    import json as json_mod
    import urllib.error
    import urllib.request

    from containerpilot_tpu.workload.serve import InferenceServer

    vanilla = InferenceServer(CFG, params, "127.0.0.1", 0,
                              max_len=MAX_LEN)
    slotted = InferenceServer(CFG, params, "127.0.0.1", 0,
                              max_len=MAX_LEN, slots=1)

    def post_status(port, body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/generate",
            data=json_mod.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=120) as resp:
                return resp.status, resp.read().decode()
        except urllib.error.HTTPError as exc:
            return exc.code, exc.read().decode()

    async def scenario():
        await vanilla.run()
        await slotted.run()
        loop = asyncio.get_event_loop()
        no_slots = await loop.run_in_executor(
            None, lambda: post_status(
                vanilla.port,
                {"tokens": [[1, 2]], "max_new_tokens": 4,
                 "stream": True},
            )
        )
        with_stop = await loop.run_in_executor(
            None, lambda: post_status(
                slotted.port,
                {"tokens": [[1, 2]], "max_new_tokens": 4,
                 "stream": True, "stop": [[3]]},
            )
        )
        await vanilla.stop()
        await slotted.stop()
        return no_slots, with_stop

    no_slots, with_stop = run(scenario())
    assert no_slots[0] == 422 and "--slots" in no_slots[1]
    assert with_stop[0] == 422 and "stop" in with_stop[1]


def test_prefix_cache_admission_matches_generate(params):
    """--prefix-cache composes with the pool: an admission with a
    cached prefix rewinds + bucket-extends instead of full prefill,
    every admission seeds the cache, and output stays byte-identical
    to solo generate — cold miss, exact-repeat hit, and the
    chat-turn partial hit (extended prompt)."""
    from containerpilot_tpu.workload.serve_prefix import PrefixCache

    pc = PrefixCache(entries=4)
    # prefill_chunk too: the cold miss takes chunked_prefill and the
    # chat-turn hit's bucketed suffix (16 > 4) takes extend_pieces —
    # the prefix path honors the same O(chunk) activation bound
    eng = SlotEngine(CFG, params, MAX_LEN, slots=2, chunk=3,
                     prefix_cache=pc, prefill_chunk=4)
    try:
        base_p = [(i * 5 + 2) % 64 for i in range(20)]  # >= MIN_REUSE
        got = eng.submit(base_p, max_new=6).result(timeout=180)
        assert got == _solo(params, base_p, 6)
        assert pc.stats["misses"] == 1 and len(pc) == 1

        # exact repeat (sampled): rewind + bucketed extend, same bytes
        got = eng.submit(base_p, max_new=6, temperature=0.7,
                         seed=3).result(timeout=180)
        assert got == _solo(params, base_p, 6, temperature=0.7, seed=3)
        assert pc.stats["hits"] == 1 and pc.stats["tokens_reused"] > 0

        # the chat-turn shape: history + a new suffix
        turn2 = base_p + [9, 9, 5]
        got = eng.submit(turn2, max_new=6).result(timeout=180)
        assert got == _solo(params, turn2, 6)
        assert pc.stats["hits"] == 2 and len(pc) == 2
    finally:
        eng.stop()


def test_prefix_cache_rejects_cp_and_window(params):
    """The fundamental non-compositions still refuse at construction:
    cached prefixes bypass the ring, and a ring cache's stale rows
    are live window context."""
    import dataclasses

    from containerpilot_tpu.parallel import MeshPlan, make_mesh
    from containerpilot_tpu.workload.serve_prefix import PrefixCache

    mesh = make_mesh(
        jax.devices()[:2], plan=MeshPlan(data=1, model=1, seq=2)
    )
    with pytest.raises(ValueError, match="bypass the ring"):
        SlotEngine(CFG, params, MAX_LEN, slots=2, chunk=3,
                   cp_mesh=mesh, prefix_cache=PrefixCache(2))
    win_cfg = dataclasses.replace(CFG, window=8)
    with pytest.raises(ValueError, match="window"):
        SlotEngine(win_cfg, params, MAX_LEN, slots=2, chunk=3,
                   prefix_cache=PrefixCache(2))


def test_slots_reject_max_len_too_small_for_warmup(params):
    """A legal but tiny --max-len must fail at construction with a
    clean message — not after the port is bound, when warmup()'s
    dummy request (4 prompt ids + chunk+1 new tokens) would hit
    submit()'s ValueError and kill the server mid-startup."""
    from containerpilot_tpu.workload.serve import InferenceServer

    with pytest.raises(ValueError, match="max_len >= slot_chunk"):
        InferenceServer(
            CFG, params, "127.0.0.1", 0, max_len=8, slots=2,
            slot_chunk=8,
        )
    # the boundary itself is fine: 4 + chunk + 1 == max_len
    InferenceServer(
        CFG, params, "127.0.0.1", 0, max_len=9, slots=1, slot_chunk=4,
    )


def test_slot_engine_composes_with_tensor_parallel():
    """The slot pool rides TP-sharded params: the vmapped decode and
    the insert/chunk programs partition under GSPMD, and output stays
    byte-identical to the single-device solo run."""
    import dataclasses

    from containerpilot_tpu.parallel import (
        MeshPlan,
        make_mesh,
        shard_params,
    )

    cfg = dataclasses.replace(CFG, d_model=64, n_heads=8, d_ff=128)
    params = init_params(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh(jax.devices()[:8], plan=MeshPlan(data=1, model=8))
    sharded = shard_params(params, mesh, cfg)

    eng = SlotEngine(cfg, sharded, MAX_LEN, slots=2, chunk=3)
    try:
        a = eng.submit([1, 2, 3], max_new=6, temperature=0.8, seed=4)
        b = eng.submit([5, 6], max_new=4)
        assert a.result(timeout=180) == _solo(
            params, [1, 2, 3], 6, cfg=cfg, temperature=0.8, seed=4
        )
        assert b.result(timeout=180) == _solo(params, [5, 6], 4, cfg=cfg)
    finally:
        eng.stop()


def test_min_new_matches_generate(params, engine):
    """min_new through the slot engine equals solo generate with the
    same floor (the mask applies at the same sample indices)."""
    tokens = [2, 4, 6]
    free = _solo(params, tokens, 6)
    eos = free[1]
    got = engine.submit(
        tokens, max_new=6, eos_id=eos, min_new=4
    ).result(timeout=120)
    assert got == _solo(
        params, tokens, 6, eos_id=eos, min_new_tokens=4
    )
    assert eos not in got[:4]
    with pytest.raises(ValueError, match="min_new"):
        engine.submit(tokens, max_new=4, min_new=5)


def test_penalties_match_generate(params, engine):
    """Penalties through the slot engine equal solo generate — the
    counts buffer reproduces the scan's bookkeeping exactly."""
    tokens = [1, 2, 3]
    kw = dict(frequency_penalty=50.0, temperature=0.7, seed=8)
    got = engine.submit(tokens, max_new=8, **kw).result(timeout=120)
    assert got == _solo(
        params, tokens, 8, temperature=0.7, seed=8,
        frequency_penalty=50.0,
    )
    assert len(set(got)) == len(got)
