"""Config loader tests (reference: config/config_test.go conventions)."""
import pytest

from containerpilot_tpu.config.loader import (
    ConfigError,
    load_config,
    new_config,
    parse_config,
)
from containerpilot_tpu.discovery import FileCatalogBackend, NoopBackend


GOOD_CONFIG = """
{
  // JSON5: comments and trailing commas are fine
  consul: "none",
  logging: { level: "DEBUG", format: "default", output: "stdout" },
  stopTimeout: "2s",
  jobs: [
    {
      name: "app",
      exec: "sleep 1",
      restarts: 1,
    },
    {
      name: "web-svc",
      exec: "sleep 1",
      port: 8080,
      interfaces: ["static:203.0.113.9"],
      health: { exec: "true", interval: 5, ttl: 15 },
    },
  ],
  watches: [
    { name: "upstream", interval: 5 },
  ],
  telemetry: {
    port: 9099,
    interfaces: ["static:127.0.0.1"],
    metrics: [
      { name: "zz_loader_sensor", help: "a sensor", type: "gauge" },
    ],
  },
}
"""


def test_full_config_parses_and_validates():
    cfg = new_config(parse_config(GOOD_CONFIG))
    assert isinstance(cfg.discovery, NoopBackend)
    assert cfg.stop_timeout == pytest.approx(2.0)
    names = [j.name for j in cfg.jobs]
    # telemetry synthesizes its self-advertising job
    assert names == ["app", "web-svc", "containerpilot"]
    assert cfg.watches[0].name == "watch.upstream"
    assert cfg.telemetry.port == 9099
    tele_job = cfg.jobs[-1]
    assert tele_job.port == 9099
    assert tele_job.heartbeat_interval == 5
    assert tele_job.ttl == 15


def test_unknown_top_level_key_rejected():
    with pytest.raises(ConfigError, match="unknown configuration keys"):
        parse_config('{ bogus: 1, jobs: [] }')


def test_stop_timeout_default():
    cfg = new_config(parse_config('{ jobs: [{name: "a", exec: "true"}] }'))
    assert cfg.stop_timeout == pytest.approx(5.0)


def test_parse_error_has_line_context():
    bad = '{\n  jobs: [\n    { name: }\n  ]\n}'
    with pytest.raises(ConfigError, match="parse error"):
        parse_config(bad)


def test_template_renders_before_parse(monkeypatch):
    monkeypatch.setenv("APP_EXEC", "sleep 9")
    cfg = new_config(
        parse_config('{ jobs: [{ name: "app", exec: "{{ .APP_EXEC }}" }] }')
    )
    assert cfg.jobs[0].exec.exec == "sleep"
    assert cfg.jobs[0].exec.args == ["9"]


def test_file_catalog_backend_from_uri(tmp_path):
    cfg = new_config(
        parse_config(
            '{ consul: "file:%s", jobs: [{name: "a", exec: "true"}] }'
            % tmp_path
        )
    )
    assert isinstance(cfg.discovery, FileCatalogBackend)


def test_load_config_from_file(tmp_path):
    path = tmp_path / "containerpilot.json5"
    path.write_text(GOOD_CONFIG)
    cfg = load_config(str(path))
    assert cfg.config_path == str(path)


def test_load_config_missing_path():
    with pytest.raises(ConfigError, match="-config flag is required"):
        load_config("")


def test_load_config_env_fallback(tmp_path, monkeypatch):
    path = tmp_path / "cp.json5"
    path.write_text('{ jobs: [{name: "a", exec: "true"}] }')
    monkeypatch.setenv("CONTAINERPILOT", str(path))
    cfg = load_config(None)
    assert cfg.jobs[0].name == "a"
