"""Event system tests: bus fan-out, actor lifetime, reload flag, ring
buffer, timers. Mirrors the reference's event-loop test conventions
(reference: events/bus_test.go, events/timer_test.go; SURVEY.md §4)."""
import asyncio

import pytest

from containerpilot_tpu.events import (
    DEBUG_RING_SIZE,
    Event,
    EventBus,
    EventCode,
    EventHandler,
    GLOBAL_SHUTDOWN,
    GLOBAL_STARTUP,
    QUIT_BY_TEST,
    cancel_timer,
    code_from_string,
    event_timeout,
    event_timer,
)


def test_event_equality_and_parse():
    assert Event(EventCode.STARTUP, "global") == GLOBAL_STARTUP
    assert Event(EventCode.EXIT_SUCCESS, "a") != Event(EventCode.EXIT_SUCCESS, "b")
    assert code_from_string("exitSuccess") is EventCode.EXIT_SUCCESS
    assert code_from_string("EXIT_SUCCESS") is EventCode.EXIT_SUCCESS
    with pytest.raises(ValueError):
        code_from_string("nope")


class CollectingActor(EventHandler):
    """Minimal actor: records every event, quits on QUIT/SHUTDOWN."""

    def __init__(self, name="actor"):
        super().__init__()
        self.name = name
        self.seen = []

    async def run(self):
        while True:
            ev = await self.next_event()
            self.seen.append(ev)
            if ev.code in (EventCode.QUIT, EventCode.SHUTDOWN):
                break
        self.unsubscribe()
        self.unregister()


def test_bus_fanout_and_wait(run):
    async def scenario():
        bus = EventBus()
        a, b = CollectingActor("a"), CollectingActor("b")
        for actor in (a, b):
            actor.subscribe(bus)
            actor.register(bus)
        ta = asyncio.ensure_future(a.run())
        tb = asyncio.ensure_future(b.run())
        bus.publish(GLOBAL_STARTUP)
        bus.publish(Event(EventCode.EXIT_SUCCESS, "job1"))
        bus.shutdown()
        reload = await bus.wait()
        await asyncio.gather(ta, tb)
        return bus, a, b, reload

    bus, a, b, reload = run(scenario())
    expected = [
        GLOBAL_STARTUP,
        Event(EventCode.EXIT_SUCCESS, "job1"),
        GLOBAL_SHUTDOWN,
    ]
    assert a.seen == expected
    assert b.seen == expected
    assert reload is False
    assert bus.debug_events() == expected


def test_bus_reload_flag(run):
    async def scenario():
        bus = EventBus()
        actor = CollectingActor()
        actor.subscribe(bus)
        actor.register(bus)
        t = asyncio.ensure_future(actor.run())
        bus.set_reload_flag()
        bus.shutdown()
        reload = await bus.wait()
        await t
        return reload

    assert run(scenario()) is True


def test_bus_wait_empty_returns_immediately(run):
    async def scenario():
        bus = EventBus()
        return await bus.wait()

    assert run(scenario()) is False


def test_quit_by_test_stops_single_actor(run):
    async def scenario():
        bus = EventBus()
        a, b = CollectingActor("a"), CollectingActor("b")
        a.subscribe(bus)
        a.register(bus)
        t = asyncio.ensure_future(a.run())
        # b never subscribes; publishing QUIT_BY_TEST only reaches a
        bus.publish(QUIT_BY_TEST)
        reload = await bus.wait()
        await t
        return a, b, reload

    a, b, reload = run(scenario())
    assert a.seen == [QUIT_BY_TEST]
    assert b.seen == []
    assert reload is False


def test_debug_ring_bounded(run):
    async def scenario():
        bus = EventBus()
        for i in range(25):
            bus.publish(Event(EventCode.METRIC, f"m{i}"))
        return bus.debug_events()

    ring = run(scenario())
    assert len(ring) == DEBUG_RING_SIZE
    assert ring[-1] == Event(EventCode.METRIC, "m24")
    assert ring[0] == Event(EventCode.METRIC, f"m{25 - DEBUG_RING_SIZE}")


def test_one_shot_timeout(run):
    async def scenario():
        bus = EventBus()
        event_timeout(bus, 0.02, "myjob.wait")
        await asyncio.sleep(0.1)
        return bus.debug_events()

    ring = run(scenario())
    assert ring == [Event(EventCode.TIMER_EXPIRED, "myjob.wait")]


def test_ticker_fires_repeatedly_until_cancelled(run):
    async def scenario():
        bus = EventBus()
        t = event_timer(bus, 0.02, "myjob.heartbeat")
        await asyncio.sleep(0.09)
        cancel_timer(t)
        count_at_cancel = len(bus.debug_events())
        await asyncio.sleep(0.05)
        return count_at_cancel, len(bus.debug_events())

    at_cancel, after = run(scenario())
    assert at_cancel >= 2
    assert after == at_cancel  # no ticks after cancellation


def test_mailbox_overflow_drops_not_deadlocks(run):
    from containerpilot_tpu.events import subscriber as subscriber_mod

    def dropped_count() -> float:
        counter = subscriber_mod._DROP_COUNTER
        if counter is None:  # pragma: no cover - prometheus is in-tree
            return float("nan")
        return counter.labels(code="metric", source="x")._value.get()

    async def scenario():
        bus = EventBus()
        actor = CollectingActor()
        actor.subscribe(bus)
        before = dropped_count()
        # never drain the mailbox; overflow must not wedge publish
        for i in range(1100):
            bus.publish(Event(EventCode.METRIC, "x"))
        return actor.rx.qsize(), dropped_count() - before

    qsize, dropped = run(scenario())
    assert qsize == 1000
    # the documented deviation from the reference (drop instead of
    # blocking the bus) is observable via the prometheus drop counter
    assert dropped == 100


def test_publish_from_foreign_thread_routes_to_home_loop(run):
    """Off-loop publishes are marshalled via call_soon_threadsafe so
    asyncio.Queue mailboxes are only touched from the home loop."""
    import threading

    async def scenario():
        bus = EventBus()
        actor = CollectingActor()
        actor.subscribe(bus)
        bus.register(actor)  # remembers the home loop
        t = threading.Thread(
            target=bus.publish, args=(Event(EventCode.METRIC, "offloop"),)
        )
        t.start()
        t.join()
        # the event must not be delivered synchronously on the foreign
        # thread; it lands once the home loop runs its callbacks
        for _ in range(50):
            if actor.rx.qsize():
                break
            await asyncio.sleep(0.01)
        return actor.rx.get_nowait()

    assert run(scenario()) == Event(EventCode.METRIC, "offloop")


def test_config_facing_event_aliases():
    """healthy/unhealthy/changed are the documented config names
    (reference: events/events.go FromString)."""
    assert code_from_string("healthy") is EventCode.STATUS_HEALTHY
    assert code_from_string("unhealthy") is EventCode.STATUS_UNHEALTHY
    assert code_from_string("changed") is EventCode.STATUS_CHANGED


# -- racecheck: the dynamic analog of cpcheck's CP-LOCKPUB --------------


def test_fanout_delivers_outside_bus_lock(run):
    """Regression guard for the bus's own publish discipline: fan-out
    must happen AFTER the internal lock is released (a subscriber
    callback that touches the bus again must never find it held)."""
    from containerpilot_tpu.analysis import RaceCheck

    async def scenario():
        rc = RaceCheck()
        bus = EventBus()
        bus._lock = rc.rlock("bus-internal")  # noqa: SLF001
        held_at_delivery = []

        class Probe(CollectingActor):
            def receive(self, event):
                held_at_delivery.append(list(rc._held()))  # noqa: SLF001
                super().receive(event)

        Probe("probe").subscribe(bus)
        bus.publish(GLOBAL_STARTUP)
        assert held_at_delivery == [[]]
        rc.assert_clean()

    run(scenario())


def test_subscriber_may_publish_from_receive(run):
    """A subscriber reacting to an event by publishing another one
    must not deadlock or corrupt fan-out: delivery runs outside the
    bus lock, over a snapshot of the subscriber list."""

    async def scenario():
        bus = EventBus()
        probe = CollectingActor("probe")

        class Reactor(CollectingActor):
            def receive(self, event):
                super().receive(event)
                if event.code is EventCode.STARTUP:
                    # re-entrant publish AND a subscription mutation
                    # mid-fan-out: both safe over the snapshot
                    CollectingActor("late").subscribe(bus)
                    bus.publish(Event(EventCode.STATUS_CHANGED, "react"))

        Reactor("reactor").subscribe(bus)
        probe.subscribe(bus)
        bus.publish(GLOBAL_STARTUP)
        codes = [e.code for e in bus.debug_events()]
        assert codes == [EventCode.STARTUP, EventCode.STATUS_CHANGED]

    run(scenario())
