"""Config-validation tables: durations, IP selection DSL, job configs.
Mirrors the reference's per-package config_test.go conventions
(reference: jobs/config_test.go, config/timing/*_test.go,
config/services/*_test.go)."""
import ipaddress

import pytest

from containerpilot_tpu.config import (
    DurationError,
    InterfaceIP,
    get_ip,
    get_timeout,
    parse_duration,
    validate_name,
)
from containerpilot_tpu.discovery import NoopBackend
from containerpilot_tpu.events import EventCode, GLOBAL_STARTUP
from containerpilot_tpu.jobs import (
    UNLIMITED,
    JobConfig,
    JobConfigError,
    new_job_configs,
)


# --- durations -------------------------------------------------------------

@pytest.mark.parametrize(
    "raw,expected",
    [
        (5, 5.0),
        ("5", 5.0),
        ("500ms", 0.5),
        ("1.5s", 1.5),
        ("1m", 60.0),
        ("1h2m3s", 3723.0),
        ("100us", 0.0001),
        (0.25, 0.25),
    ],
)
def test_parse_duration_ok(raw, expected):
    assert parse_duration(raw) == pytest.approx(expected)


@pytest.mark.parametrize("raw", ["nope", "5x", "", None, True, [1]])
def test_parse_duration_bad(raw):
    with pytest.raises(DurationError):
        parse_duration(raw)


def test_get_timeout_empty_is_zero():
    assert get_timeout("") == 0.0
    assert get_timeout(None) == 0.0
    assert get_timeout("10ms") == pytest.approx(0.01)


# --- names -----------------------------------------------------------------

def test_validate_name():
    validate_name("my-service2")
    for bad in ("", "Big", "2fast", "under_score", "x"):
        with pytest.raises(ValueError):
            validate_name(bad)


# --- interface/IP DSL ------------------------------------------------------

FAKE_IPS = [
    InterfaceIP("eth0", ipaddress.IPv4Address("10.2.0.5")),
    InterfaceIP("eth0", ipaddress.IPv4Address("192.168.1.4")),
    InterfaceIP("eth1", ipaddress.IPv4Address("172.16.0.7")),
    InterfaceIP("eth1", ipaddress.IPv6Address("fdc6:238c:c4bc::1")),
    InterfaceIP("lo", ipaddress.IPv4Address("127.0.0.1")),
]


@pytest.mark.parametrize(
    "specs,expected",
    [
        (["eth0"], "10.2.0.5"),
        (["eth0[1]"], "192.168.1.4"),
        (["eth1"], "172.16.0.7"),
        (["eth1:inet6"], "fdc6:238c:c4bc::1"),
        (["inet"], "10.2.0.5"),
        (["inet6"], "fdc6:238c:c4bc::1"),
        (["192.168.0.0/16"], "192.168.1.4"),
        (["static:203.0.113.5"], "203.0.113.5"),
        (["bogus0", "eth1"], "172.16.0.7"),  # ordered fallback
    ],
)
def test_get_ip_specs(specs, expected):
    assert get_ip(specs, interface_ips=FAKE_IPS) == expected


def test_get_ip_no_match_raises():
    with pytest.raises(ValueError):
        get_ip(["bogus0"], interface_ips=FAKE_IPS)


def test_get_ip_bad_spec():
    with pytest.raises(ValueError):
        get_ip(["static:notanip"], interface_ips=FAKE_IPS)
    with pytest.raises(ValueError):
        get_ip(["eth0[x]"], interface_ips=FAKE_IPS)


# --- job configs -----------------------------------------------------------

def test_when_defaults_to_global_startup():
    cfg = JobConfig({"name": "app", "exec": "true"}).validate(None)
    assert cfg.when_event == GLOBAL_STARTUP
    assert cfg.when_starts_limit == 1
    assert cfg.restart_limit == 0


def test_when_mutual_exclusion():
    with pytest.raises(JobConfigError):
        JobConfig(
            {
                "name": "app",
                "exec": "true",
                "when": {"interval": "5s", "once": "healthy"},
            }
        ).validate(None)


def test_interval_too_small():
    with pytest.raises(JobConfigError):
        JobConfig(
            {"name": "app", "exec": "true", "when": {"interval": "100us"}}
        ).validate(None)


def test_interval_defaults():
    cfg = JobConfig(
        {"name": "app", "exec": "true", "when": {"interval": "5s"}}
    ).validate(None)
    assert cfg.restart_limit == UNLIMITED  # interval jobs restart forever
    assert cfg.exec_timeout == pytest.approx(5.0)  # timeout = interval


def test_each_unlimited_restarts_forbidden():
    with pytest.raises(JobConfigError):
        JobConfig(
            {
                "name": "app",
                "exec": "true",
                "restarts": "unlimited",
                "when": {"each": "changed", "source": "watch.backend"},
            }
        ).validate(None)


@pytest.mark.parametrize(
    "restarts,expected",
    [("never", 0), ("unlimited", UNLIMITED), (3, 3), ("3", 3), (1.2, 1)],
)
def test_restarts_parsing(restarts, expected):
    cfg = JobConfig(
        {"name": "app", "exec": "true", "restarts": restarts}
    ).validate(None)
    assert cfg.restart_limit == expected


@pytest.mark.parametrize("restarts", ["sometimes", -1, True, []])
def test_restarts_invalid(restarts):
    with pytest.raises(JobConfigError):
        JobConfig(
            {"name": "app", "exec": "true", "restarts": restarts}
        ).validate(None)


def test_signal_source_forces_unlimited_starts():
    cfg = JobConfig(
        {"name": "app", "exec": "true", "when": {"source": "SIGHUP"}}
    ).validate(None)
    assert cfg.when_event.code == EventCode.SIGNAL
    assert cfg.when_starts_limit == UNLIMITED


def test_port_requires_health():
    with pytest.raises(JobConfigError):
        JobConfig({"name": "app", "exec": "true", "port": 80}).validate(
            NoopBackend()
        )


def test_health_requires_interval_and_ttl():
    for health in ({"exec": "true", "ttl": 5}, {"exec": "true", "interval": 5}):
        with pytest.raises(JobConfigError):
            JobConfig(
                {"name": "app", "exec": "true", "port": 80, "health": health}
            ).validate(NoopBackend())


def test_advertised_job_builds_service_definition():
    cfg = JobConfig(
        {
            "name": "web-app",
            "exec": "true",
            "port": 8080,
            "tags": ["v1"],
            "interfaces": ["static:203.0.113.5"],
            "health": {"exec": "true", "interval": 5, "ttl": 15},
        }
    ).validate(NoopBackend())
    svc = cfg.service_definition
    assert svc is not None
    assert svc.registration.address == "203.0.113.5"
    assert svc.registration.ttl == 15
    assert svc.registration.id.startswith("web-app-")


def test_bad_service_name_rejected():
    with pytest.raises(JobConfigError):
        JobConfig(
            {
                "name": "Bad_Name",
                "exec": "true",
                "port": 80,
                "interfaces": ["static:10.0.0.1"],
                "health": {"exec": "true", "interval": 5, "ttl": 15},
            }
        ).validate(NoopBackend())


def test_unknown_keys_rejected():
    with pytest.raises(JobConfigError):
        JobConfig({"name": "app", "exec": "true", "bogus": 1})


def test_name_defaults_to_exec():
    cfg = JobConfig({"exec": "/bin/true --flag"}).validate(None)
    assert cfg.name == "/bin/true"


def test_stop_dependency_wiring():
    configs = new_job_configs(
        [
            {"name": "main", "exec": "sleep 1"},
            {
                "name": "prestop",
                "exec": "true",
                "when": {"once": "stopping", "source": "main"},
            },
        ],
        None,
    )
    main = next(c for c in configs if c.name == "main")
    assert main.stopping_wait_event.code == EventCode.STOPPED
    assert main.stopping_wait_event.source == "prestop"


def test_initial_status_validation():
    with pytest.raises(JobConfigError):
        JobConfig(
            {
                "name": "app",
                "exec": "true",
                "port": 80,
                "initial_status": "bogus",
                "interfaces": ["static:10.0.0.1"],
                "health": {"exec": "true", "interval": 5, "ttl": 15},
            }
        ).validate(NoopBackend())


def test_weakly_typed_numeric_fields():
    """String numbers are valid ports/intervals/ttls, matching the
    reference's mapstructure WeaklyTypedInput decoding."""
    cfg = JobConfig(
        {
            "name": "app",
            "exec": "true",
            "port": "8080",
            "interfaces": ["static:10.0.0.1"],
            "health": {"exec": "true", "interval": "5", "ttl": "15"},
        }
    ).validate(NoopBackend())
    assert cfg.port == 8080
    assert cfg.heartbeat_interval == 5.0
    assert cfg.ttl == 15
    with pytest.raises(JobConfigError, match="port must be an integer"):
        JobConfig({"name": "app", "exec": "true", "port": "eighty"})

    from containerpilot_tpu.watches import WatchConfig

    wcfg = WatchConfig({"name": "backend", "interval": "7"}).validate(
        NoopBackend()
    )
    assert wcfg.poll == 7


def test_coerce_int_accepts_integral_floats():
    from containerpilot_tpu.config.decode import coerce_int, coerce_number

    assert coerce_int("8080") == 8080
    assert coerce_int(8080.0) == 8080
    assert coerce_int("8080.0") == 8080
    assert coerce_int("eighty") is None
    assert coerce_int(80.5) is None
    assert coerce_number("7.5") == 7.5
    cfg = JobConfig(
        {
            "name": "app", "exec": "true", "port": 8080.0,
            "interfaces": ["static:10.0.0.1"],
            "health": {"exec": "true", "interval": 5, "ttl": 15},
        }
    ).validate(NoopBackend())
    assert cfg.port == 8080


def test_health_logging_must_be_object():
    with pytest.raises(JobConfigError, match="health.logging must be"):
        JobConfig(
            {
                "name": "app", "exec": "true",
                "health": {"exec": "x", "interval": 1, "ttl": 1,
                           "logging": [1]},
            }
        ).validate(None)
