"""Step-program interface + fused K-round decode windows
(models/stepprog.py, models/slots.py::decode_slots_window,
models/speculative.py::SpeculativeStepProgram): byte parity between
fused and sequential decode at the models level AND the engine level,
speculative-as-step-program parity with speculative_generate,
cancel-mid-window retirement with the PR 9 decode-accounting
contract, and honest dispatch counters under fusion."""
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from containerpilot_tpu.models.decode import (
    BIAS_SLOTS_MAX,
    _jitted_prefill,
    generate,
)
from containerpilot_tpu.models.slots import (
    admit_slot_state,
    decode_slots_chunk,
    decode_slots_window,
    first_sample,
    init_slot_state,
    insert_row,
    slot_cache,
)
from containerpilot_tpu.models.transformer import (
    TransformerConfig,
    init_params,
)
from containerpilot_tpu.workload.serve_slots import SlotEngine

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
    max_seq_len=64, dtype=jnp.float32,
)
MAX_LEN = 48


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


def _solo(params, tokens, max_new, cfg=CFG, **kw):
    """Solo generate with the server key convention, server-trimmed."""
    seed = kw.pop("seed", 0)
    eos = kw.pop("eos_id", -1)
    out = generate(
        params, jnp.asarray([tokens], jnp.int32), cfg, max_new,
        MAX_LEN,
        rng=jnp.stack([jax.random.fold_in(jax.random.PRNGKey(seed), 0)]),
        eos_id=eos, **kw,
    )
    row = [int(t) for t in np.asarray(out)[0]]
    if eos >= 0 and eos in row:
        row = row[: row.index(eos) + 1]
    return row


def _admitted_pool(params, tokens, seed=7, temperature=0.8, top_k=12):
    """A 2-slot pool with one sampled request admitted at slot 0 —
    shared setup for the models-level window-vs-sequential tests."""
    pool = slot_cache(CFG, 2, MAX_LEN)
    state = init_slot_state(CFG, 2)
    prompt = jnp.asarray([tokens], jnp.int32)
    logits, row = _jitted_prefill(CFG, MAX_LEN)(params, prompt)
    key = jax.random.fold_in(jax.random.PRNGKey(seed), 0)
    bias_idx = jnp.full((BIAS_SLOTS_MAX,), -1, jnp.int32)
    bias_val = jnp.zeros((BIAS_SLOTS_MAX,), jnp.float32)
    first = first_sample(
        logits, key, temperature, top_k, 0.0, CFG,
        bias_idx=bias_idx, bias_val=bias_val,
    )
    pool = insert_row(pool, row, 0, CFG)
    state = admit_slot_state(
        state, 0, CFG, last=first, key=key,
        temperature=temperature, top_k=top_k, top_p=0.0, eos_id=-1,
        pad_id=0, min_new=0, presence=0.0, frequency=0.0,
        bias_idx=bias_idx, bias_val=bias_val, done=False,
    )
    return pool, state


def test_window_matches_sequential_chunks(params):
    """The tentpole's byte-parity contract at the models level: one
    fused K-round window emits bit-identical tokens to K sequential
    decode_slots_chunk dispatches AND leaves every state leaf
    bit-identical — the window's while_loop body is the same traced
    per-step scan, so this is equality by construction, pinned."""
    chunk, k_rounds = 3, 4
    pool, state = _admitted_pool(params, [1, 2, 3, 4])
    seq_toks = []
    for _ in range(k_rounds):
        pool, state, toks = decode_slots_chunk(
            params, pool, state, CFG, chunk
        )
        seq_toks.append(np.asarray(jax.device_get(toks)))
    sequential = np.concatenate(seq_toks, axis=1)
    seq_state = {
        name: np.asarray(jax.device_get(leaf))
        for name, leaf in state.items()
    }

    pool2, state2 = _admitted_pool(params, [1, 2, 3, 4])
    budget = np.asarray([chunk * k_rounds, 0], np.int32)
    pool2, state2, toks, run = decode_slots_window(
        params, pool2, state2, CFG, chunk, k_rounds, budget
    )
    assert int(jax.device_get(run)) == k_rounds
    assert np.array_equal(
        np.asarray(jax.device_get(toks)), sequential
    )
    for name, leaf in state2.items():
        assert np.array_equal(
            np.asarray(jax.device_get(leaf)), seq_state[name]
        ), f"state leaf {name} diverged"


def test_window_early_exit_on_budget_and_done(params):
    """The device loop stops once every slot is done or out of
    budget: a 2-token budget exits after one 3-token round, and the
    skipped rounds' token columns stay at pad."""
    chunk, k_rounds = 3, 4
    pool, state = _admitted_pool(params, [1, 2, 3, 4])
    # one reference round for the executed prefix
    ref_pool, ref_state = _admitted_pool(params, [1, 2, 3, 4])
    _rp, _rs, ref = decode_slots_chunk(
        params, ref_pool, ref_state, CFG, chunk
    )
    ref = np.asarray(jax.device_get(ref))

    pool, state, toks, run = decode_slots_window(
        params, pool, state, CFG, chunk, k_rounds,
        np.asarray([2, 0], np.int32),
    )
    toks = np.asarray(jax.device_get(toks))
    assert int(jax.device_get(run)) == 1
    assert np.array_equal(toks[:, :chunk], ref)
    assert (toks[:, chunk:] == 0).all()  # pad_id 0 fill
    # an all-dead pool (budget 0 everywhere) runs zero rounds
    pool, state, toks, run = decode_slots_window(
        params, pool, state, CFG, chunk, k_rounds,
        np.zeros((2,), np.int32),
    )
    assert int(jax.device_get(run)) == 0


@pytest.mark.parametrize("window", [2, 4])
def test_engine_fused_parity_with_window_one(params, window):
    """Engine-level byte parity: the same request mix — greedy,
    sampled, eos-stopped, penalized — produces identical outputs on a
    fused engine and a window=1 engine, and both match solo
    generate."""
    reqs = [
        ([1, 2, 3, 4], dict(max_new=12)),
        ([5, 6, 7], dict(max_new=9, temperature=0.9, top_k=12,
                         top_p=0.8, seed=11)),
        ([1, 2, 3], dict(max_new=8, temperature=0.7, seed=8,
                         frequency_penalty=50.0)),
    ]
    results = {}
    for w in (1, window):
        eng = SlotEngine(CFG, params, MAX_LEN, slots=2, chunk=3,
                         window=w)
        try:
            futs = [eng.submit(list(t), **dict(kw)) for t, kw in reqs]
            results[w] = [f.result(timeout=180) for f in futs]
        finally:
            eng.stop()
    assert results[1] == results[window]
    for (tokens, kw), got in zip(reqs, results[window]):
        kw = dict(kw)
        max_new = kw.pop("max_new")
        assert got == _solo(params, tokens, max_new, **kw)


def test_engine_fused_eos_parity(params):
    """eos inside a fused window trims exactly like generate: the row
    keeps the eos, drops the pads after it."""
    tokens = [2, 4, 6]
    free = _solo(params, tokens, 9)
    eos = free[1]
    eng = SlotEngine(CFG, params, MAX_LEN, slots=2, chunk=3, window=4)
    try:
        got = eng.submit(tokens, max_new=9, eos_id=eos).result(
            timeout=120
        )
    finally:
        eng.stop()
    assert got == _solo(params, tokens, 9, eos_id=eos)
    assert got[-1] == eos


def test_fused_dispatch_counters_honest(params):
    """dispatches bumps once per DEVICE dispatch (not per fused
    round) and tokens_out counts every round's emissions: a K=4
    engine decodes the same long request with well under half the
    K=1 engine's dispatches/token."""
    dpt = {}
    for w in (1, 4):
        eng = SlotEngine(CFG, params, MAX_LEN, slots=2, chunk=3,
                         window=w)
        try:
            # warm admission programs, then snapshot
            eng.submit([1, 2], max_new=2).result(timeout=120)
            d0, t0 = eng.dispatches, eng.tokens_out
            out = eng.submit([1, 2, 3, 4], max_new=36).result(
                timeout=180
            )
            assert len(out) == 36
            d, t = eng.dispatches - d0, eng.tokens_out - t0
            assert t >= 36  # every round's emissions counted
            dpt[w] = d / t
        finally:
            eng.stop()
    assert dpt[4] <= 0.5 * dpt[1], dpt


def test_cancel_mid_window_retires_within_one_window(params):
    """A cancel lands at the NEXT window boundary, not the end of the
    generation: the slot frees with a partial emission and the
    request's engine timings carry the abandon-instant ``done`` stamp
    (decode accounted up to the abandon, the PR 9 tracing
    contract)."""
    eng = SlotEngine(CFG, params, MAX_LEN, slots=2, chunk=2, window=4)
    try:
        cancel = threading.Event()
        first = threading.Event()
        timings = {}

        def on_tokens(_delta):
            first.set()

        max_new = MAX_LEN - 3
        fut = eng.submit(
            [5, 6, 7], max_new=max_new, on_tokens=on_tokens,
            cancel=cancel, timings=timings,
        )
        assert first.wait(timeout=120), "no first token"
        abandoned_at = time.monotonic()
        cancel.set()
        got = fut.result(timeout=120)
        assert 0 < len(got) < max_new, (
            f"cancel did not stop decode early ({len(got)}/{max_new})"
        )
        # the engine stamped done at the sweep (>= the abandon
        # instant, within the one-window reaction bound) and recorded
        # the rounds the row actually rode
        assert timings["done"] >= timings["admitted"]
        assert timings["done"] >= abandoned_at
        assert timings["rounds"] >= 1
        # the slot is back; the pool keeps serving with parity
        deadline = time.monotonic() + 30
        while eng.stats["active"]:
            assert time.monotonic() < deadline
            time.sleep(0.05)
        after = eng.submit([1, 2, 3, 4], max_new=7).result(timeout=120)
        assert after == _solo(params, [1, 2, 3, 4], 7)
    finally:
        eng.stop()


# ---------------------------------------------------------- programs


def test_make_step_program_picks_quantized():
    from containerpilot_tpu.models.quantized import (
        QuantizedStepProgram,
        quantize_model_params,
    )
    from containerpilot_tpu.models.stepprog import (
        PlainStepProgram,
        make_step_program,
    )

    params = init_params(jax.random.PRNGKey(0), CFG)
    plain = make_step_program(CFG, params, MAX_LEN, 2, 3)
    assert type(plain) is PlainStepProgram
    qparams = quantize_model_params(params)
    quant = make_step_program(CFG, qparams, MAX_LEN, 2, 3, rounds=4)
    assert isinstance(quant, QuantizedStepProgram)
    assert quant.rounds == 4
    # a full-precision pytree must fail loudly, not serve 4x HBM
    with pytest.raises(ValueError, match="quantize_model_params"):
        QuantizedStepProgram(CFG, params, MAX_LEN, 2, 3)


def test_quantized_program_decodes_through_engine():
    """int8 weights under the fused engine: the engine drives the
    quantized step program end to end and output matches the
    quantized params' own solo generate (same weights, same keys)."""
    from containerpilot_tpu.models.quantized import (
        quantize_model_params,
    )

    params = init_params(jax.random.PRNGKey(0), CFG)
    qparams = quantize_model_params(params)
    eng = SlotEngine(CFG, qparams, MAX_LEN, slots=2, chunk=3,
                     window=4)
    try:
        assert type(eng.program).__name__ == "QuantizedStepProgram"
        got = eng.submit([1, 2, 3], max_new=8).result(timeout=180)
        assert got == _solo(qparams, [1, 2, 3], 8)
    finally:
        eng.stop()


def _spec_setup():
    from containerpilot_tpu.models.speculative import (
        layer_prefix_draft,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    dparams, dcfg = layer_prefix_draft(params, cfg, 1)
    return cfg, params, dcfg, dparams


def test_speculative_program_matches_speculative_generate():
    """The speculative step program through the engine emits exactly
    what speculative_generate emits (trimmed) on the same prompts —
    greedy, eos-stopped, and max_new-capped."""
    from containerpilot_tpu.models.speculative import (
        SpeculativeStepProgram,
        speculative_generate,
    )

    cfg, params, dcfg, dparams = _spec_setup()
    eng = SlotEngine(
        cfg, params, MAX_LEN,
        program=SpeculativeStepProgram(
            cfg, dcfg, params, dparams, MAX_LEN, speculate=4
        ),
    )
    try:
        assert eng.stats["slots"] == 1
        cases = [([1, 2, 3, 4], 12, -1), ([5, 6], 10, -1)]
        # derive an eos that actually occurs mid-stream
        ref, _ = speculative_generate(
            params, dparams, jnp.asarray([[2, 4, 6]], jnp.int32),
            cfg, dcfg, max_new_tokens=16, max_len=MAX_LEN,
            speculate=4,
        )
        cases.append(([2, 4, 6], 16, int(np.asarray(ref)[0][1])))
        ref_rounds = 0
        for tokens, max_new, eos in cases:
            ref, stats = speculative_generate(
                params, dparams, jnp.asarray([tokens], jnp.int32),
                cfg, dcfg, max_new_tokens=max_new, max_len=MAX_LEN,
                speculate=4, eos_id=eos,
            )
            ref_rounds += stats["rounds"]
            ref_row = [int(t) for t in np.asarray(ref)[0]]
            if eos >= 0 and eos in ref_row:
                ref_row = ref_row[: ref_row.index(eos) + 1]
            got = eng.submit(tokens, max_new=max_new,
                             eos_id=eos).result(timeout=180)
            assert got == ref_row, (tokens, got, ref_row)
        # dispatch honesty, exactly: one dispatch per admission plus
        # dispatch_cost=2 (draft + verify) per round — and the engine
        # rode the SAME round count the standalone loop did (same k
        # clamps, same eos/max_new exits)
        assert eng.dispatches == len(cases) + 2 * ref_rounds
    finally:
        eng.stop()


def test_speculative_program_rejects_bad_shapes():
    import dataclasses

    from containerpilot_tpu.models.speculative import (
        SpeculativeStepProgram,
    )

    cfg, params, dcfg, dparams = _spec_setup()
    with pytest.raises(ValueError, match="speculate"):
        SpeculativeStepProgram(cfg, dcfg, params, dparams, MAX_LEN,
                               speculate=0)
    win = dataclasses.replace(cfg, window=8)
    with pytest.raises(ValueError, match="window"):
        SpeculativeStepProgram(win, dcfg, params, dparams, MAX_LEN)


def test_server_speculative_rides_engine(run):
    """Server-level: a greedy /v1/generate on a --draft-layers server
    routes through the speculative ENGINE (not serve_strategies),
    matches plain greedy decode, and folds its dispatch/token pair
    into /v1/model + /v1/goodput."""
    import asyncio
    import json
    import urllib.request

    from containerpilot_tpu.workload.serve import InferenceServer

    cfg, params, _dcfg, _dparams = _spec_setup()
    server = InferenceServer(
        cfg, params, "127.0.0.1", 0, max_len=MAX_LEN,
        draft_layers=1, speculate=4,
    )

    def fetch(path, body=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}{path}",
            data=json.dumps(body).encode() if body is not None
            else None,
            headers={"Content-Type": "application/json"}
            if body else {},
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            return json.loads(resp.read().decode())

    async def scenario():
        await server.run()
        loop = asyncio.get_event_loop()
        out = await loop.run_in_executor(
            None, lambda: fetch(
                "/v1/generate",
                {"tokens": [[1, 2, 3]], "max_new_tokens": 10},
            )
        )
        info = await loop.run_in_executor(
            None, lambda: fetch("/v1/model")
        )
        gp = await loop.run_in_executor(
            None, lambda: fetch("/v1/goodput")
        )
        await server.stop()
        return out, info, gp

    out, info, gp = run(scenario())
    expect = _solo(params, [1, 2, 3], 10, cfg=cfg)
    assert out["tokens"][0] == expect
    spec = info["speculative"]
    assert spec["engine"]["slots"] == 1
    assert spec["engine"]["dispatches"] >= 1
    # the spec engine's counters fold into the goodput pair
    assert gp["dispatches"] >= spec["engine"]["dispatches"]
    assert gp["tokens_out"] >= len(expect)


def test_tiny_max_len_clamps_window(params):
    """A max_len too small for the fused warmup request clamps the
    server's engine back to window 1 instead of leaving the fused
    program to compile under a live request (the boundary the
    PR-guard test pins stays valid: 4 + chunk + 1 == max_len)."""
    from containerpilot_tpu.workload.serve import InferenceServer

    server = InferenceServer(
        CFG, params, "127.0.0.1", 0, max_len=9, slots=1, slot_chunk=4,
    )
    assert server.slot_engine.window == 1
    roomy = InferenceServer(
        CFG, params, "127.0.0.1", 0, max_len=MAX_LEN, slots=1,
        slot_chunk=4,
    )
    assert roomy.slot_engine.window == 4


def test_warmup_fingerprint_includes_window():
    from containerpilot_tpu.workload.modelcfg import warmup_fingerprint

    a = warmup_fingerprint(CFG, MAX_LEN, slots=2, slot_chunk=4,
                           slot_window=1)
    b = warmup_fingerprint(CFG, MAX_LEN, slots=2, slot_chunk=4,
                           slot_window=4)
    assert a != b
