"""Template-rendering tests (reference: config/template/template_test.go
behavior parity)."""
import pytest

from containerpilot_tpu.config.template import TemplateError, apply_template


ENV = {
    "NAME": "world",
    "EMPTY": "",
    "CSV": "a,b,c",
    "HOST": "10.0.0.5:8080",
    "COUNT": "3",
}


def render(src, env=ENV):
    return apply_template(src, env)


def test_plain_text_passthrough():
    assert render("no actions here { } ") == "no actions here { } "


def test_variable_substitution():
    assert render("hello {{ .NAME }}!") == "hello world!"


def test_missing_variable_renders_empty():
    assert render("[{{ .NOPE }}]") == "[]"


def test_default_pipeline():
    assert render('{{ .NOPE | default "fallback" }}') == "fallback"
    assert render('{{ .NAME | default "fallback" }}') == "world"
    assert render('{{ .EMPTY | default "fallback" }}') == "fallback"


def test_default_direct_call():
    assert render('{{ default "fb" .NOPE }}') == "fb"


def test_env_function(monkeypatch):
    monkeypatch.setenv("SOME_ENV_VAR", "from-env")
    assert render('{{ env "SOME_ENV_VAR" }}') == "from-env"


def test_split_and_join():
    assert render('{{ .CSV | split "," | join ";" }}') == "a;b;c"


def test_replace_all():
    assert render('{{ .HOST | replaceAll ":8080" "" }}') == "10.0.0.5"


def test_regex_replace_all():
    assert render('{{ .HOST | regexReplaceAll ":[0-9]+$" "" }}') == "10.0.0.5"
    assert (
        render('{{ .HOST | regexReplaceAll "([0-9.]+):.*" "$1" }}')
        == "10.0.0.5"
    )


def test_loop_range():
    assert render("{{ range loop 3 }}x{{ end }}") == "xxx"
    assert render("{{ range loop 1 4 }}{{ . }} {{ end }}") == "1 2 3 "
    assert render("{{ range loop 3 1 }}{{ . }}{{ end }}") == "32"


def test_loop_env_var_count():
    assert render("{{ range loop 0 .COUNT }}y{{ end }}") == "yyy"


def test_if_else():
    assert render("{{ if .NAME }}yes{{ else }}no{{ end }}") == "yes"
    assert render("{{ if .EMPTY }}yes{{ else }}no{{ end }}") == "no"
    assert render("{{ if .NOPE }}yes{{ end }}") == ""


def test_nested_parens():
    assert render('{{ join "," (split "," .CSV) }}') == "a,b,c"


def test_unknown_function_raises():
    with pytest.raises(TemplateError):
        render("{{ bogus 1 }}")


def test_unclosed_block_raises():
    with pytest.raises(TemplateError):
        render("{{ if .NAME }}never closed")


def test_eq_ne_builtins():
    """Go text/template's eq/ne builtins (variadic eq: true when the
    first arg equals ANY other), usable inside if blocks — what the
    multihost example uses to pick frontend vs follower health."""
    assert render(
        '{{ if eq (.ROLE | default "0") "0" }}front{{ else }}'
        "follow{{ end }}", {"ROLE": ""}
    ) == "front"
    assert render(
        '{{ if eq .ROLE "0" "1" }}low{{ else }}high{{ end }}',
        {"ROLE": "3"},
    ) == "high"
    assert render(
        '{{ if ne .ROLE "0" }}yes{{ end }}', {"ROLE": "3"}
    ) == "yes"
    with pytest.raises(TemplateError):
        render("{{ eq .ROLE }}", {"ROLE": "x"})


def test_eq_cross_type_raises():
    """Go's eq errors on incompatible types; env values are strings,
    so `eq .COUNT 2` must fail loudly, not silently pick a branch."""
    with pytest.raises(TemplateError, match="incompatible"):
        render("{{ if eq .COUNT 3 }}x{{ end }}")


def test_eq_int_vs_float_raises():
    """Go treats int vs float literals as incomparable basic kinds
    (``eq 1 1.0`` errors); Python's 1 == 1.0 must not silently
    diverge from the reference's wire behavior."""
    with pytest.raises(TemplateError, match="incompatible"):
        render("{{ if eq 1 1.0 }}x{{ end }}")
    with pytest.raises(TemplateError, match="incompatible"):
        render("{{ if ne 2.0 2 }}x{{ end }}")
    # matching kinds still compare fine
    assert render("{{ if eq 1.5 1.5 }}y{{ end }}") == "y"
