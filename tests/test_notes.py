"""fleet/notes.py: the heartbeat note-wire registry.

One producer + one tolerant parser per field, registered in FIELDS —
these tests pin the roundtrip (a member-emitted note decodes back
field-for-field through the registered parsers), the duck-typed
producer surface, and the tolerant-parser discipline. The static face
of the same contract (no ad-hoc ``"x=" +`` bypasses, no unregistered
consumption) is CP-NOTEWIRE in tests/test_analysis.py.
"""
import math

import pytest

from containerpilot_tpu.fleet import notes
from containerpilot_tpu.fleet.notes import (
    FIELDS,
    ROLE_ACTIVE,
    encode_compile_cache,
    field_names,
    member_note,
    parse_compile_cache,
    parse_field,
    parse_occ,
    split_note,
)


class _Server:
    """The full duck-typed member surface, every field populated."""

    occupancy = 0.5
    role = "standby"

    def compile_cache_note(self):
        return encode_compile_cache("beef", "/tmp/cache dir")

    def kv_note(self):
        return "5,2,160,1,1"

    def prefix_digest_note(self):
        return "v7:" + "ab" * 16

    def goodput_note(self):
        return "1.000,2.000,3.000,0.100,0.200,0.000,0.000,4,40"

    def migrate_note(self):
        return "2,3,0,0,1"


def test_registry_is_the_whole_vocabulary():
    assert field_names() == {
        "occ", "role", "cc", "kv", "pd", "gp", "mg",
    }
    for spec in FIELDS:
        assert spec.doc, f"{spec.name} must document itself"
        assert callable(spec.produce) and callable(spec.parse)


def test_member_note_roundtrips_through_registered_parsers():
    note = member_note(_Server())
    assert note.startswith("ok ")
    fields = split_note(note)
    assert set(fields) == field_names()
    assert parse_field("occ", fields["occ"]) == 0.5
    assert parse_field("role", fields["role"]) == "standby"
    digest, cache_dir = parse_field("cc", fields["cc"])
    assert (digest, cache_dir) == ("beef", "/tmp/cache dir")
    assert parse_field("kv", fields["kv"]) == {
        "hits": 5, "misses": 2, "tokens_reused": 160,
        "spilled": 1, "readmitted": 1,
    }
    version, fingerprints = parse_field("pd", fields["pd"])
    assert version == 7 and len(fingerprints) == 1
    gp = parse_field("gp", fields["gp"])
    assert gp["dispatches"] == 4 and gp["tokens_out"] == 40
    counters, landed = parse_field("mg", fields["mg"])
    assert counters["done"] == 2 and counters["total"] == 3
    assert landed == {}


def test_member_note_emits_in_registry_order():
    note = member_note(_Server())
    emitted = [part.partition("=")[0] for part in note.split()[1:]]
    assert emitted == [
        spec.name for spec in FIELDS
        if spec.produce(_Server())
    ]


def test_bare_server_emits_just_ok():
    """Every producer duck-types: an object with none of the optional
    accessors advertises nothing beyond liveness."""
    assert member_note(object()) == "ok"


def test_active_role_advertises_by_omission():
    class _Active(_Server):
        role = ROLE_ACTIVE

    assert "role=" not in member_note(_Active())
    # and absent role decodes to "" — caller defaults it to active
    assert parse_field("role", split_note("ok").get("role", "")) == ""


def test_parse_occ_is_tolerant():
    assert parse_occ("0.50") == 0.5
    assert parse_occ("2.5") == 1.0      # clamped
    assert parse_occ("-1") == 0.0
    assert parse_occ("nan") is None
    assert parse_occ("inf") is None
    assert parse_occ("bogus") is None
    assert parse_occ("") is None
    assert parse_occ(None) is None
    assert parse_occ(math.pi) is None   # non-str input


def test_compile_cache_codec_tolerance():
    assert parse_compile_cache("beef:%2Ftmp%2Fcc") == ("beef", "/tmp/cc")
    assert parse_compile_cache("no-colon") == ("", "")
    assert parse_compile_cache(":/tmp/cc") == ("", "")
    assert parse_compile_cache("beef:") == ("", "")
    assert parse_compile_cache(None) == ("", "")
    assert encode_compile_cache("beef", "") == ""


def test_parse_field_rejects_unregistered_names():
    with pytest.raises(KeyError):
        parse_field("zz", "1")


def test_producers_omit_empty_values():
    class _Partial:
        occupancy = 0.25

        def kv_note(self):
            return ""  # counters all zero -> producer yields empty

    note = member_note(_Partial())
    assert note == "ok occ=0.25"


def test_import_does_not_pull_jax():
    """notes is imported by the gateway, which must come up without
    jax; the cc codec lives HERE (modelcfg delegates via lazy import)
    for exactly that reason."""
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "-c",
         "import sys; import containerpilot_tpu.fleet.notes; "
         "sys.exit(1 if 'jax' in sys.modules else 0)"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
