"""Job state-machine tests: event-sequence assertions against the real
bus, mirroring the reference's harness (reference: jobs/jobs_test.go —
TestJobRunSafeClose, TestJobRunStartupTimeout, restart/interval/
stop-dependency/maintenance coverage; SURVEY.md §4)."""
import asyncio

import pytest

from containerpilot_tpu.discovery import NoopBackend
from containerpilot_tpu.events import (
    Event,
    EventBus,
    EventCode,
    GLOBAL_ENTER_MAINTENANCE,
    GLOBAL_SHUTDOWN,
    GLOBAL_STARTUP,
)
from containerpilot_tpu.jobs import Job, JobConfig, new_job_configs


def make_job(raw, disc=None):
    cfg = JobConfig(raw)
    cfg.validate(disc)
    return Job(cfg)


async def start_jobs(bus, *jobs):
    tasks = []
    for job in jobs:
        job.subscribe(bus)
        job.register(bus)
    for job in jobs:
        tasks.append(job.run())
    return tasks


def test_job_run_safe_close(run):
    """One-shot job: startup -> exec -> exit -> stopping/stopped."""

    async def scenario():
        bus = EventBus()
        job = make_job({"name": "myjob", "exec": "true"})
        tasks = await start_jobs(bus, job)
        bus.publish(GLOBAL_STARTUP)
        await bus.wait()
        await asyncio.gather(*tasks)
        return bus.debug_events(), job

    ring, job = run(scenario())
    assert ring == [
        GLOBAL_STARTUP,
        Event(EventCode.EXIT_SUCCESS, "myjob"),
        Event(EventCode.STOPPING, "myjob"),
        Event(EventCode.STOPPED, "myjob"),
    ]
    assert job.is_complete


def test_job_startup_timeout(run):
    """A when-event that never arrives: the wait-timeout quits the job
    (reference: jobs_test.go TestJobRunStartupTimeout)."""

    async def scenario():
        bus = EventBus()
        job = make_job(
            {
                "name": "myjob",
                "exec": "true",
                "when": {"once": "startup", "source": "never", "timeout": "100ms"},
            }
        )
        tasks = await start_jobs(bus, job)
        bus.publish(GLOBAL_STARTUP)
        await bus.wait()
        await asyncio.gather(*tasks)
        return bus.debug_events()

    ring = run(scenario())
    assert ring == [
        GLOBAL_STARTUP,
        Event(EventCode.TIMER_EXPIRED, "myjob"),
        Event(EventCode.STOPPING, "myjob"),
        Event(EventCode.STOPPED, "myjob"),
    ]


def test_restart_budget_consumed(run):
    """restarts: 2 -> exec runs exactly 3 times then the job halts."""

    async def scenario():
        bus = EventBus()
        job = make_job({"name": "flaky", "exec": "false", "restarts": 2})
        tasks = await start_jobs(bus, job)
        bus.publish(GLOBAL_STARTUP)
        await bus.wait()
        await asyncio.gather(*tasks)
        return bus.debug_events()

    ring = run(scenario())
    exits = [e for e in ring if e == Event(EventCode.EXIT_FAILED, "flaky")]
    assert len(exits) == 3  # initial run + 2 restarts


def test_interval_job_runs_repeatedly(run):
    """when.interval drives periodic runs; exits don't halt it."""

    async def scenario():
        bus = EventBus()
        job = make_job(
            {"name": "cron", "exec": "true", "when": {"interval": "50ms"}}
        )
        tasks = await start_jobs(bus, job)
        bus.publish(GLOBAL_STARTUP)
        await asyncio.sleep(0.3)
        bus.shutdown()
        await bus.wait()
        await asyncio.gather(*tasks)
        return bus.debug_events()

    ring = run(scenario())
    runs = [e for e in ring if e == Event(EventCode.EXIT_SUCCESS, "cron")]
    assert len(runs) >= 2


def test_stop_dependency_handshake(run):
    """main's cleanup waits for the pre-stop job's STOPPED before
    publishing its own STOPPED (reference: jobs.go:295-312,388-416)."""

    async def scenario():
        bus = EventBus()
        configs = new_job_configs(
            [
                {"name": "main", "exec": "sleep 10", "stopTimeout": "2s"},
                {
                    "name": "prestop",
                    "exec": ["/bin/sh", "-c", "echo bye"],
                    "when": {"once": "stopping", "source": "main"},
                },
            ],
            None,
        )
        jobs = [Job(c) for c in configs]
        tasks = await start_jobs(bus, *jobs)
        bus.publish(GLOBAL_STARTUP)
        await asyncio.sleep(0.1)
        bus.shutdown()
        await bus.wait()
        await asyncio.gather(*tasks)
        jobs[0].kill()  # reap the sleep
        await asyncio.sleep(0.1)  # let the exec waiter task finish
        return bus.debug_events()

    ring = run(scenario(), timeout=15)
    # main STOPPED must come after prestop STOPPED
    idx_prestop = ring.index(Event(EventCode.STOPPED, "prestop"))
    idx_main = ring.index(Event(EventCode.STOPPED, "main"))
    assert idx_prestop < idx_main


def test_health_check_drives_status_and_heartbeat(run):
    """Heartbeat timer -> health exec -> StatusHealthy + catalog TTL."""

    async def scenario():
        disc = NoopBackend()
        bus = EventBus()
        job = make_job(
            {
                "name": "web",
                "exec": "sleep 10",
                "port": 8000,
                "interfaces": ["static:10.0.0.1"],
                "health": {"exec": "true", "interval": 1, "ttl": 5},
            },
            disc,
        )
        job.heartbeat = 0.05  # speed up the tick for the test
        tasks = await start_jobs(bus, job)
        bus.publish(GLOBAL_STARTUP)
        await asyncio.sleep(0.3)
        healthy_seen = Event(EventCode.STATUS_HEALTHY, "web") in bus.debug_events()
        bus.shutdown()
        await bus.wait()
        await asyncio.gather(*tasks)
        job.kill()
        await asyncio.sleep(0.1)  # let the exec waiter task finish
        return disc, healthy_seen

    disc, healthy_seen = run(scenario(), timeout=15)
    assert healthy_seen
    assert disc.ttl_updates  # TTL refreshed at least once
    assert disc.registered == {}  # deregistered during cleanup


def test_maintenance_deregisters_and_mutes_checks(run):
    async def scenario():
        disc = NoopBackend()
        bus = EventBus()
        job = make_job(
            {
                "name": "web",
                "exec": "sleep 10",
                "port": 8000,
                "interfaces": ["static:10.0.0.1"],
                "health": {"exec": "true", "interval": 1, "ttl": 5},
            },
            disc,
        )
        job.heartbeat = 0.05
        tasks = await start_jobs(bus, job)
        bus.publish(GLOBAL_STARTUP)
        await asyncio.sleep(0.15)  # get registered via a passing check
        registered_before = dict(disc.registered)
        bus.publish(GLOBAL_ENTER_MAINTENANCE)
        await asyncio.sleep(0.05)
        ttl_count = len(disc.ttl_updates)
        await asyncio.sleep(0.15)  # heartbeats during maintenance: none
        ttl_after = len(disc.ttl_updates)
        status = job.get_status()
        bus.shutdown()
        await bus.wait()
        await asyncio.gather(*tasks)
        job.kill()
        await asyncio.sleep(0.1)  # let the exec waiter task finish
        return registered_before, ttl_count, ttl_after, status

    registered_before, ttl_count, ttl_after, status = run(scenario(), timeout=15)
    assert registered_before  # was registered before maintenance
    assert ttl_after == ttl_count  # no TTL refresh while in maintenance
    assert str(status) == "maintenance"


def test_sighup_triggered_job(run):
    """when.source: SIGHUP runs the exec on each Signal event
    (reference: jobs.go:226-228,351-357; core/signals.go:24-27)."""

    async def scenario():
        bus = EventBus()
        job = make_job(
            {"name": "reloader", "exec": "true", "when": {"source": "SIGHUP"}}
        )
        tasks = await start_jobs(bus, job)
        bus.publish(GLOBAL_STARTUP)
        await asyncio.sleep(0.05)
        bus.publish(Event(EventCode.SIGNAL, "SIGHUP"))
        await asyncio.sleep(0.2)
        ran_once = Event(EventCode.EXIT_SUCCESS, "reloader") in bus.debug_events()
        bus.publish(Event(EventCode.SIGNAL, "SIGHUP"))
        await asyncio.sleep(0.2)
        runs = [
            e
            for e in bus.debug_events()
            if e == Event(EventCode.EXIT_SUCCESS, "reloader")
        ]
        bus.shutdown()
        await bus.wait()
        await asyncio.gather(*tasks)
        return ran_once, len(runs)

    ran_once, total = run(scenario(), timeout=15)
    assert ran_once
    assert total >= 2


def test_heartbeat_self_heals_after_catalog_loss(run, tmp_path):
    """If the catalog loses our registration (restart, wipe), the next
    heartbeat re-registers instead of warning forever."""
    import shutil

    from containerpilot_tpu.discovery import FileCatalogBackend

    async def scenario():
        disc = FileCatalogBackend(str(tmp_path / "cat"))
        bus = EventBus()
        job = make_job(
            {
                "name": "web",
                "exec": "sleep 10",
                "port": 8000,
                "interfaces": ["static:10.0.0.1"],
                "health": {"exec": "true", "interval": 1, "ttl": 5},
            },
            disc,
        )
        job.heartbeat = 0.05
        tasks = await start_jobs(bus, job)
        bus.publish(GLOBAL_STARTUP)
        await asyncio.sleep(0.3)
        assert disc.instances("web"), "registered initially"
        # catalog wiped out from under us
        shutil.rmtree(str(tmp_path / "cat" / "services" / "web"))
        await asyncio.sleep(0.4)  # one failed TTL + a healing heartbeat
        healed = bool(disc.instances("web"))
        bus.shutdown()
        await bus.wait()
        await asyncio.gather(*tasks)
        job.kill()
        await asyncio.sleep(0.1)
        return healed

    assert run(scenario(), timeout=15)
