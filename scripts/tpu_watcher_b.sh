#!/usr/bin/env bash
# Round-5 second-pass watcher: the first session landed the tuned
# table and the attention numbers but training/int8/decode failed on
# tunnel flake + two first-exposure bench bugs (fixed since). Loop:
# when the tunnel answers and no session is running, re-run the FULL
# bench (tuned routing, fixed int8 padded path, split decode/admission
# benches) and write the capture to a NEW timestamped snapshot ONLY
# when the training bench produced an mfu (the headline the round
# needs). The round-5 snapshot is a historical artifact the committed
# narrative (CHANGELOG/PARITY) cites by number — a re-run must never
# cp-replace it (ADVICE r5); each capture gets its own file. Log to
# /tmp/tpu_watcher_b_log.txt.
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/tpu_watcher_b_log.txt
DONE=/tmp/tpu_round5b_done

note() { echo "$(date -u +%FT%TZ) $*" >> "$LOG"; }

note "watcher-b started (pid $$)"
while true; do
    if [ -e "$DONE" ]; then
        note "done marker present; watcher-b exiting"
        exit 0
    fi
    if pgrep -f 'python bench.py' >/dev/null 2>&1; then
        sleep 60
        continue
    fi
    if timeout 120 python -c "
import jax
assert any(d.platform != 'cpu' for d in jax.devices())
" >/dev/null 2>&1; then
        note "tunnel healthy: running bench"
        if timeout 12600 python bench.py > /tmp/bench_out_b.json 2>/tmp/bench_err_b.log; then
            if python - <<'EOF'
import json, sys
j = json.load(open("/tmp/bench_out_b.json"))
t = j.get("extras", {}).get("training", {})
sys.exit(0 if "mfu" in t else 1)
EOF
            then
                SNAP="docs/bench-snapshots/round5b-rerun-$(date -u +%Y%m%dT%H%M%SZ).json"
                cp /tmp/bench_out_b.json "$SNAP"
                touch "$DONE"
                note "bench succeeded with mfu; wrote $SNAP; done"
                exit 0
            else
                note "bench ran but no training mfu; will retry"
            fi
        else
            note "bench run failed/timed out; will retry"
        fi
        sleep 60
    else
        note "tunnel down; waiting"
        sleep 180
    fi
done
