#!/usr/bin/env bash
# Keep the one-shot TPU measurement session (tpu_session.sh) alive
# across tunnel flaps. Every minute: if a session is running, leave it
# alone (ONE TPU client at a time); if none is running and the round-5
# snapshot hasn't landed, probe the device and relaunch the session
# the moment the tunnel answers. Log to /tmp/tpu_watcher_log.txt.
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/tpu_watcher_log.txt
SNAP_GLOB="docs/bench-snapshots/round5-*.json"

note() { echo "$(date -u +%FT%TZ) $*" >> "$LOG"; }

note "watcher started (pid $$)"
while true; do
    # shellcheck disable=SC2086
    if ls $SNAP_GLOB >/dev/null 2>&1; then
        note "snapshot present; watcher done"
        exit 0
    fi
    if pgrep -f 'scripts/tpu_session.sh' >/dev/null 2>&1 \
       || pgrep -f 'containerpilot_tpu.ops.autotune' >/dev/null 2>&1 \
       || pgrep -f 'python bench.py' >/dev/null 2>&1; then
        sleep 60
        continue
    fi
    if timeout 120 python -c "
import jax
assert any(d.platform != 'cpu' for d in jax.devices())
" >/dev/null 2>&1; then
        note "tunnel healthy + no session running: relaunching"
        nohup bash scripts/tpu_session.sh > /tmp/tpu_session_r5.log 2>&1 &
        sleep 120
    else
        note "tunnel down; waiting"
        sleep 180
    fi
done
