#!/usr/bin/env python
"""trace-smoke: prove the cross-hop stitched timeline on a live fleet.

Boots the ``make fleet-smoke`` topology for real — two in-process
``InferenceServer`` replicas (slot engine on, so SSE works), a
``FleetMember`` each heartbeating a file catalog, one ``FleetGateway``
over the cp-mux/1 transport (the default) — then issues ONE buffered
and ONE SSE ``/v1/generate`` through the gateway and asserts, for
each, from ``GET /v1/traces``:

- **stitched, >= 2 hops**: the gateway's timeline for that trace id
  carries both gateway-side spans (admission_queue_wait,
  upstream_connect/ttfb) and spliced ``replica.*`` spans, and the
  SAME trace id appears in one replica's own /v1/traces ring — two
  processes' views of one request, joined by the id the gateway
  minted;
- **non-overlapping stage accounting within tolerance**: the
  top-level gateway stages partition the request — their summed
  duration never exceeds the trace's wall time by more than the
  tolerance — and every replica child span lands inside the trace
  window (clock skew across hops is bounded by the in-process
  network, so the alignment at the dispatch span must hold);
- **over mux**: the replica that served it shows opened mux streams
  on the gateway's /fleet snapshot (the hop really rode cp-mux/1).

Exit 0 on success, 1 with the offending evidence on stderr.
Wired as ``make trace-smoke`` next to ``fleet-smoke``.
"""
import asyncio
import json
import os
import sys
import tempfile
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

from containerpilot_tpu.discovery import FileCatalogBackend  # noqa: E402
from containerpilot_tpu.fleet import FleetGateway, FleetMember  # noqa: E402
from containerpilot_tpu.models.transformer import (  # noqa: E402
    TransformerConfig,
    init_params,
)
from containerpilot_tpu.workload.serve import InferenceServer  # noqa: E402

#: slack for summed-stage accounting and replica-span alignment (ms):
#: covers timer granularity + the header-write gap between span ends
#: and trace finish on a loaded 1-core box
TOLERANCE_MS = 25.0
SERVICE = "inference"


def _get(port: int, path: str):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=30
    ) as resp:
        return resp.status, resp.read(), dict(resp.headers)


def _post(port: int, payload: dict):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/generate",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=60) as resp:
        return resp.status, resp.read(), dict(resp.headers)


def _post_sse(port: int, payload: dict):
    """Read a whole SSE response; returns (trace_id_header, events)."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/generate",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=60) as resp:
        headers = dict(resp.headers)
        raw = resp.read()
    events = []
    for blob in raw.split(b"\n\n"):
        if blob.startswith(b"data: "):
            events.append(json.loads(blob[len(b"data: "):]))
    return headers, events


def _fail(msg: str, evidence=None) -> None:
    print(f"trace-smoke: FAIL: {msg}", file=sys.stderr)
    if evidence is not None:
        print(json.dumps(evidence, indent=2)[:4000], file=sys.stderr)
    raise SystemExit(1)


def _find_trace(snapshot: dict, trace_id: str) -> dict:
    for entry in snapshot["recent"] + snapshot["slowest"]:
        if entry["trace_id"] == trace_id:
            return entry
    _fail(f"trace {trace_id} not in /v1/traces", snapshot)


def _check_stitched(entry: dict, want_stages) -> None:
    stages = {s["stage"] for s in entry["spans"]}
    missing = set(want_stages) - stages
    if missing:
        _fail(f"{entry['trace_id']}: missing stages {missing}", entry)
    if not any(s.startswith("replica.") for s in stages):
        _fail(
            f"{entry['trace_id']}: no replica.* spans — the timeline "
            f"is single-hop, not stitched", entry,
        )


def _check_accounting(entry: dict) -> None:
    duration = entry["duration_ms"]
    top_sum = sum(
        s["dur_ms"]
        for s in entry["spans"]
        if not s["stage"].startswith("replica.")
    )
    if top_sum > duration + TOLERANCE_MS:
        _fail(
            f"{entry['trace_id']}: top-level stages sum to "
            f"{top_sum:.2f}ms > duration {duration:.2f}ms + "
            f"{TOLERANCE_MS}ms — stages overlap", entry,
        )
    for s in entry["spans"]:
        if not s["stage"].startswith("replica."):
            continue
        if s["offset_ms"] < -TOLERANCE_MS or (
            s["offset_ms"] + s["dur_ms"] > duration + TOLERANCE_MS
        ):
            _fail(
                f"{entry['trace_id']}: replica span {s['stage']} "
                f"falls outside the trace window", entry,
            )


async def main() -> int:
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    servers, members = [], []
    with tempfile.TemporaryDirectory(prefix="trace-smoke-") as root:
        backend = FileCatalogBackend(root)
        for i in range(2):
            server = InferenceServer(
                cfg, params, "127.0.0.1", 0, max_len=64,
                slots=2, slot_chunk=4,
            )
            await server.run()
            member = FleetMember(
                server, backend, SERVICE, ttl=30,
                heartbeat_interval=0.2, instance_id=f"replica-{i}",
            )
            await member.start()
            servers.append(server)
            members.append(member)
        gateway = FleetGateway(
            backend, SERVICE, "127.0.0.1", 0,
            poll_interval=0.2, hedge=False,
        )
        await gateway.run()
        for _ in range(200):
            if gateway.replica_count == 2:
                break
            await asyncio.sleep(0.05)
        if gateway.replica_count != 2:
            _fail(f"fleet never converged: {gateway.replica_count}/2")

        loop = asyncio.get_event_loop()
        # one buffered, one SSE — both ride cp-mux/1 (the default)
        status, _body, headers = await loop.run_in_executor(
            None, _post, gateway.port,
            {"tokens": [[1, 2, 3]], "max_new_tokens": 6, "seed": 1},
        )
        if status != 200:
            _fail(f"buffered request answered {status}")
        buffered_id = headers.get("X-CP-Trace", "")
        if not buffered_id:
            _fail("buffered answer carried no X-CP-Trace", headers)
        if not headers.get("X-CP-Span-Digest"):
            _fail("buffered answer carried no span digest", headers)
        sse_headers, events = await loop.run_in_executor(
            None, _post_sse, gateway.port,
            {
                "tokens": [[4, 5, 6]], "max_new_tokens": 6,
                "seed": 2, "stream": True,
            },
        )
        if not events or events[-1].get("done") is not True:
            _fail("SSE stream ended without its done event", events)
        sse_id = sse_headers.get("X-CP-Trace", "")
        if not sse_id:
            _fail("SSE answer carried no X-CP-Trace", sse_headers)
        if not isinstance(events[-1].get("spans"), str):
            _fail(
                "SSE done frame carried no replica span digest",
                events[-1],
            )

        _status, body, _ = await loop.run_in_executor(
            None, _get, gateway.port, "/v1/traces"
        )
        snapshot = json.loads(body)
        buffered = _find_trace(snapshot, buffered_id)
        streamed = _find_trace(snapshot, sse_id)
        _check_stitched(
            buffered,
            ("admission_queue_wait", "upstream_connect",
             "upstream_ttfb", "replica.prefill", "replica.decode"),
        )
        _check_stitched(
            streamed,
            ("admission_queue_wait", "upstream_ttfb", "relay",
             "replica.prefill", "replica.stream_relay"),
        )
        _check_accounting(buffered)
        _check_accounting(streamed)

        # cross-hop for real: the SAME ids live in a replica's ring
        for trace_id in (buffered_id, sse_id):
            found = False
            for server in servers:
                _s, body, _h = await loop.run_in_executor(
                    None, _get, server.port, "/v1/traces"
                )
                replica_snap = json.loads(body)
                if any(
                    e["trace_id"] == trace_id
                    for e in replica_snap["recent"]
                ):
                    found = True
                    break
            if not found:
                _fail(
                    f"trace {trace_id} not found in any replica's "
                    f"/v1/traces — the id did not propagate"
                )

        # and it rode mux: the gateway opened streams to its replicas
        _s, body, _h = await loop.run_in_executor(
            None, _get, gateway.port, "/fleet"
        )
        fleet = json.loads(body)
        opened = sum(
            r["mux"]["streams_opened"] for r in fleet["replicas"]
        )
        if opened < 2:
            _fail(
                f"only {opened} mux streams opened — the hops did "
                f"not ride cp-mux/1", fleet,
            )
        if fleet.get("catalog_poll_age_s") is None:
            _fail("/fleet reports no catalog_poll_age_s", fleet)

        await gateway.stop()
        for member in members:
            await member.stop()
        for server in servers:
            await server.stop()

    print(
        "trace-smoke: OK — buffered "
        f"{buffered_id} ({buffered['duration_ms']}ms, dominant "
        f"{buffered.get('dominant_stage')}) and SSE {sse_id} "
        f"({streamed['duration_ms']}ms, dominant "
        f"{streamed.get('dominant_stage')}) stitched across "
        "gateway + replica over cp-mux/1"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(asyncio.run(main()))
