#!/bin/sh
# Endurance soak: reload churn under a crash-looping job, health
# checks, a watch, and telemetry; samples supervisor RSS per cycle.
# Reproduces the README endurance claim:
#   scripts/soak.sh [cycles=60] [period_seconds=55]
# Pass/fail: prints FIRST/LAST RSS and grep counts of exceptions; a
# healthy run holds RSS flat and reports zero exceptions.
set -eu

cd "$(dirname "$0")/.."  # the package runs from the repo root

CYCLES=${1:-60}
PERIOD=${2:-55}
DIR=$(mktemp -d /tmp/cp-soak.XXXXXX)
CFG="$DIR/soak.json5"

cat > "$CFG" <<EOF
{
  consul: "file:$DIR/cat",
  stopTimeout: "500ms",
  control: { socket: "$DIR/s.socket" },
  telemetry: { port: 19500, interfaces: ["static:127.0.0.1"] },
  jobs: [
    { name: "steady", exec: ["/bin/sh", "-c", "while true; do sleep 0.5; done"],
      restarts: "unlimited", port: 7500, interfaces: ["static:127.0.0.1"],
      health: { exec: "true", interval: 1, ttl: 5 } },
    { name: "crashy", exec: ["/bin/sh", "-c", "sleep 1; exit 1"], restarts: "unlimited" },
    { name: "tick", exec: "true", when: { interval: "500ms" } },
  ],
  watches: [{ name: "steady", interval: 1 }],
}
EOF

python -m containerpilot_tpu -config "$CFG" > "$DIR/sup.log" 2>&1 &
SUP=$!
trap 'kill -TERM $SUP 2>/dev/null || true' EXIT

sleep 3
if ! python -m containerpilot_tpu -config "$CFG" -ping >/dev/null 2>&1; then
  echo "FAIL: supervisor did not come up; log:" >&2
  tail -5 "$DIR/sup.log" >&2
  exit 1
fi

i=0
while [ "$i" -lt "$CYCLES" ]; do
  sleep "$PERIOD"
  python -m containerpilot_tpu -config "$CFG" -reload >/dev/null 2>&1 || true
  ps -o rss= -p "$SUP" >> "$DIR/rss.log" 2>/dev/null || break
  i=$((i + 1))
done

DONE=$(wc -l < "$DIR/rss.log" 2>/dev/null || echo 0)
echo "cycles completed: $DONE / $CYCLES"
echo "rss first/last KB: $(head -1 "$DIR/rss.log") / $(tail -1 "$DIR/rss.log")"
echo "exceptions: $(grep -ciE 'traceback|exception|TTL failed' "$DIR/sup.log" || true)"
echo "artifacts: $DIR"
[ "$DONE" -eq "$CYCLES" ] || { echo "FAIL: supervisor died mid-soak" >&2; exit 1; }
