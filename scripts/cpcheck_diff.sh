#!/usr/bin/env bash
# cpcheck findings for ONLY the .py files your working tree changed —
# the fast precommit-style loop (the full gate is `make lint`; CI runs
# it via the tier-1 test_lint_gate test). The rule set is whatever
# `python -m containerpilot_tpu.analysis --list-rules` prints —
# lexical rules (CP-HOTSYNC..CP-RETRACE) and the interprocedural ones
# (CP-ASYNCREACH, CP-HOTREACH, CP-LOCKORDER, CP-NOTEWIRE) alike. The
# call graph is always built over the FULL package (a changed helper
# can create a reachability finding whose witness spans unchanged
# files); only the findings are filtered to the diff, so this stays a
# few-seconds run (~4s for the whole package, AST forest parsed once).
#
# Usage:
#   scripts/cpcheck_diff.sh                 # changed vs HEAD (staged + unstaged + untracked)
#   scripts/cpcheck_diff.sh origin/main     # changed vs a base ref
#   scripts/cpcheck_diff.sh --since <ref>   # same, reads better in scripts (`make lint-diff SINCE=...`)
#
# Exits 0 when nothing relevant changed or every finding is baselined;
# non-zero on any new finding (same contract as `make lint`).
set -euo pipefail

cd "$(dirname "$0")/.."
BASE="HEAD"
case "${1:-}" in
    --since)
        [ $# -ge 2 ] || {
            echo "cpcheck_diff: --since needs a ref" >&2
            exit 2
        }
        BASE="$2"
        ;;
    "") ;;
    *) BASE="$1" ;;
esac

# a typo'd ref must fail loudly, not scan nothing and exit 0 (process
# substitution below would swallow git's error)
git rev-parse --verify --quiet "$BASE^{commit}" >/dev/null || {
    echo "cpcheck_diff: unknown base ref: $BASE" >&2
    exit 2
}

mapfile -t files < <(
    {
        git diff --name-only --diff-filter=d "$BASE" -- 'containerpilot_tpu/*.py'
        git ls-files --others --exclude-standard -- 'containerpilot_tpu/*.py'
    } | sort -u
)

if [ "${#files[@]}" -eq 0 ]; then
    echo "cpcheck_diff: no changed python files under containerpilot_tpu/"
    exit 0
fi

echo "cpcheck_diff: scanning ${#files[@]} changed file(s) vs ${BASE}"
exec "${PYTHON:-python}" -m containerpilot_tpu.analysis --files "${files[@]}"
