#!/usr/bin/env bash
# One-shot TPU measurement session: run the moment the device tunnel
# is healthy. Strict ordering — ONE TPU-touching process at a time
# (the tunnel serves a single client):
#   1. flash block autotune  -> containerpilot_tpu/ops/tuned/<platform>.json
#   2. full bench.py         -> docs/bench-snapshots/round5-<platform>.json
# Both artifacts are meant to be committed: the tuned table changes
# routing (ops/tuning.py), the snapshot is the round's evidence.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== probe =="
timeout 300 python -c "
import jax
ds = jax.devices()
assert any(d.platform != 'cpu' for d in ds), ds
print('backend:', ds[0].platform, ds[0].device_kind)
"

echo "== autotune (writes ops/tuned/<platform>.json) =="
timeout 3600 python -m containerpilot_tpu.ops.autotune \
  --seqs 1024,2048,4096,8192 --blocks 128,256,512 --write

echo "== bench (full, with tuned routing) =="
SNAP="docs/bench-snapshots/round5-$(python - <<'EOF'
import sys
sys.path.insert(0, ".")
from containerpilot_tpu.ops.tuning import platform_slug
print(platform_slug())
EOF
).json"
timeout 7200 python bench.py > /tmp/bench_out.json
cp /tmp/bench_out.json "$SNAP"
echo "snapshot: $SNAP"
tail -c 2000 "$SNAP"
